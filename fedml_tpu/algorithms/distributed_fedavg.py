"""Actor-based distributed FedAvg over the message-passing runtime.

Redesign of ``fedml_api/distributed/fedavg`` (5-file pattern:
``FedAvgAPI.py`` init + rank split, ``FedAVGAggregator``, ``FedAVGTrainer``,
``FedAvgServerManager``/``FedAvgClientManager``, ``message_define.py``).
The actor shell is for TRUE cross-process deployments (multi-host DCN);
compute inside each actor is the same jitted local update as the compiled
simulator, so the math is identical to :class:`FedAvgSim` by construction.

Topology (reference ``FedAvgAPI.py:36-66``): rank 0 = server, rank i>=1
trains the partition of client ``cohort[i-1]`` each round.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.config import ExperimentConfig
from fedml_tpu.core import adversary as A
from fedml_tpu.core.anatomy import ANATOMY
from fedml_tpu.core import compress as CMP
from fedml_tpu.core import elastic as E
from fedml_tpu.core import export as EXPORT
from fedml_tpu.core import memscope as MEMSCOPE
from fedml_tpu.core import robust, telemetry
from fedml_tpu.core import tree as T
from fedml_tpu.core.membership import MembershipLedger
from fedml_tpu.core.reputation import QuarantinePolicy, ReputationTracker
from fedml_tpu.core.manager import ClientManager, ServerManager
from fedml_tpu.core.message import (
    KEY_CLIENT_INDEX,
    KEY_COMPRESSED,
    KEY_MODEL_PARAMS,
    KEY_NUM_SAMPLES,
    KEY_ROUND,
    MSG_TYPE_C2S_JOIN,
    MSG_TYPE_C2S_LEAVE,
    MSG_TYPE_C2S_RESULT,
    MSG_TYPE_FINISH,
    MSG_TYPE_S2C_SYNC_MODEL,
    MSG_TYPE_S2C_WELCOME,
    Message,
)
from fedml_tpu.core.transport.base import BaseTransport
from fedml_tpu.data.federated import FederatedData, arrays_and_batch
from fedml_tpu.algorithms.base import build_local_update, make_task
from fedml_tpu.algorithms.fedavg import (
    ServerState,
    local_reducer,
    make_server_optimizer,
    server_update,
)
from fedml_tpu.core import random as RND
from fedml_tpu.models.base import FedModel


@dataclasses.dataclass(frozen=True)
class RoundPolicy:
    """Straggler tolerance for the actor-based server (Server Averaging
    for FL, arxiv 2103.11619: a server that makes progress from whatever
    subset of updates actually arrives).

    - ``quorum_fraction``: fraction of the round's LIVE workers whose
      results suffice to close the round once the deadline fires
      (aggregation weights renormalize over the survivors — the weighted
      mean divides by the survivors' sample mass). 1.0 + no deadline ==
      the strict everyone-reports behavior, byte-identical to the
      compiled simulator.
    - ``round_deadline_s``: wall-clock budget per round. When it expires
      with quorum met, the round closes without the stragglers; without
      quorum, the run aborts with a diagnostic instead of hanging.
      ``None`` disables the deadline (crashed peers are still handled
      via the heartbeat dead-peer callback).
    - ``recovery_extensions``: how many times a deadline that fires
      UNDER quorum re-arms for the same round instead of aborting —
      under a supervisor a crashed rank is typically seconds from being
      restarted and rejoining, so the hard quorum-lost abort only fires
      once recovery has had its chance (docs/FAULT_TOLERANCE.md
      "Recovery"). 0 (the default) keeps the PR-1 abort-at-first-expiry
      behavior.
    """

    quorum_fraction: float = 1.0
    round_deadline_s: float | None = None
    recovery_extensions: int = 0

    def __post_init__(self):
        if not (0.0 < self.quorum_fraction <= 1.0):
            raise ValueError(
                f"quorum_fraction must be in (0, 1], "
                f"got {self.quorum_fraction}"
            )
        if self.round_deadline_s is not None and self.round_deadline_s <= 0:
            raise ValueError(
                f"round_deadline_s must be positive or None, "
                f"got {self.round_deadline_s}"
            )
        if self.recovery_extensions < 0:
            raise ValueError(
                f"recovery_extensions must be >= 0, "
                f"got {self.recovery_extensions}"
            )
        if self.recovery_extensions and self.round_deadline_s is None:
            raise ValueError(
                "recovery_extensions requires round_deadline_s: "
                "extensions re-arm the round deadline, so without one "
                "there is nothing to extend and the quorum-lost abort "
                "would still fire immediately"
            )


class QuorumLostError(RuntimeError):
    """The server could not assemble a quorum of client results (too many
    crashed/straggling ranks). Carries the server's diagnostic."""


def _result_is_finite(params, n_k: float) -> bool:
    """True iff a client result carries only finite values (floating
    leaves checked; integer leaves are finite by construction)."""
    if not math.isfinite(n_k):
        return False
    for leaf in jax.tree.leaves(params):
        a = np.asarray(leaf)
        if np.issubdtype(a.dtype, np.floating) and not np.all(
            np.isfinite(a)
        ):
            return False
    return True


class FedAvgServerActor(ServerManager):
    """Rank-0 aggregator (reference ``FedAVGServerManager`` +
    ``FedAVGAggregator``) with straggler-tolerant rounds: the round
    closes when every live worker reports, when the deadline fires with
    a quorum of results in hand, or aborts loudly when the quorum is
    unreachable — the server never blocks forever on a crashed client."""

    def __init__(
        self,
        size: int,
        transport: BaseTransport,
        model: FedModel,
        cfg: ExperimentConfig,
        num_clients: int,
        on_round_done: Callable[[int, dict], None] | None = None,
        initial_variables=None,
        steps_per_epoch: int | None = None,
        batch_size: int | None = None,
        data: FederatedData | None = None,
        round_policy: RoundPolicy | None = None,
        checkpointer=None,
        checkpoint_every: int = 1,
        quarantine: QuarantinePolicy | None = None,
    ):
        super().__init__(0, size, transport)
        self.cfg = cfg
        self.num_clients = num_clients
        self.model = model
        variables = (
            initial_variables
            if initial_variables is not None
            else model.init(jax.random.key(cfg.seed))
        )
        opt = make_server_optimizer(
            cfg.fed.server_optimizer, cfg.fed.server_lr,
            cfg.fed.server_momentum,
        )
        # full ServerState so EVERY server rule the compiled sim supports
        # (FedOpt adam/adagrad/yogi pseudo-gradients, FedNova
        # tau-normalization + gmf momentum, robust clip/noise/median/
        # trimmed-mean) runs over the actor runtime too — the transport
        # zoo's second consumer (ref fedopt/FedOptAggregator.py)
        self.state = ServerState(
            variables=variables,
            opt_state=opt.init(variables["params"]),
            momentum=jax.tree.map(jnp.zeros_like, variables["params"]),
            round=jnp.asarray(0, jnp.int32),
        )
        # FedNova's tau normalization needs the RESOLVED batch size and
        # steps_per_epoch (arrays_and_batch handles full-batch mode and
        # batch > max_n clamping) — pass `data` or the explicit values;
        # raw cfg.data.batch_size would silently skew tau.
        if data is not None and (steps_per_epoch is None
                                 or batch_size is None):
            arrays, rbatch = arrays_and_batch(data, cfg.data)
            batch_size = rbatch if batch_size is None else batch_size
            if steps_per_epoch is None:
                steps_per_epoch = arrays.max_client_samples // rbatch
        if cfg.fed.algorithm == "fednova" and (
            steps_per_epoch is None or batch_size is None
        ):
            raise ValueError(
                "fednova server rule needs BOTH steps_per_epoch and "
                "batch_size (the RESOLVED values — full-batch mode and "
                "batch > max_n clamping change them): pass data= to "
                "resolve automatically, or both values explicitly"
            )
        # explicit 0 is a caller bug (would silently skew FedNova tau if
        # coerced to 1) — reject rather than repair
        if steps_per_epoch is not None and steps_per_epoch < 1:
            raise ValueError(
                f"steps_per_epoch must be >= 1, got {steps_per_epoch}"
            )
        if batch_size is not None and batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self.steps_per_epoch = 1 if steps_per_epoch is None else steps_per_epoch
        self.batch_size = cfg.data.batch_size if batch_size is None else batch_size
        self.root_key = jax.random.key(cfg.seed)
        self.round_idx = 0
        self._round_t0 = time.monotonic()
        # perf observability (core/perf.py, docs/OBSERVABILITY.md
        # "Performance observability"): the idle-gap signal fires its
        # flight-recorder event once per process, not once per round
        self._idle_gap_flagged = False
        self._results: dict[int, tuple[dict, float]] = {}
        self._lock = threading.Lock()
        self.on_round_done = on_round_done
        self.done = threading.Event()
        self.round_policy = (
            round_policy if round_policy is not None else RoundPolicy()
        )
        # -- elastic membership (docs/FAULT_TOLERANCE.md "Elastic
        # membership"): the ledger — not the launch world_size — is the
        # source of truth for who is served. JOINs from ranks beyond
        # the launch world are admitted mid-run with a stable client
        # id; MSG_TYPE_C2S_LEAVE marks a graceful departure (no restart
        # budget, no suspicion); eviction is permanent. The ledger
        # rides the round checkpoint so a SIGKILLed server restores the
        # grown/shrunk world, not the launch flag's.
        self._ledger = MembershipLedger(size, num_clients)
        self.dead_peers: set[int] = set()
        self.failure: str | None = None  # quorum-lost diagnostic
        self._deadline_timer: threading.Timer | None = None
        # generation stamp carried by every armed deadline timer:
        # Timer.cancel() is a no-op once the callback has STARTED (it
        # may already be blocked on self._lock), so a superseded timer
        # is also invalidated by its stale generation — without this, a
        # timer racing the recovery-extension re-arm could abort (or
        # burn an extra extension) inside the freshly-opened window
        self._deadline_gen = 0
        # deadline-under-quorum re-arms already spent on the current
        # round (RoundPolicy.recovery_extensions); reset per round
        self._extensions_used = 0
        # the CURRENT round's broadcast payload ``(round_idx, host_vars,
        # cohort)``, stashed by start_round so a mid-round rejoiner gets
        # the EXACT sync its cohort-mates got (a WELCOME built from live
        # state could race a round close and ship the next round's model
        # under this round's tag)
        self._round_sync: tuple[int, dict, np.ndarray] | None = None
        # rank -> round of its last WELCOME: a rejoiner re-announces
        # JOIN every 0.5 s until its first inbound, and with a large
        # model the WELCOME can take longer than that — the duplicates
        # must refresh its watchdog, not re-serialize the full model
        # (or re-count the rejoin)
        self._welcomed: dict[int, int] = {}
        # -- durable rounds (docs/FAULT_TOLERANCE.md "Recovery"): with a
        # RoundCheckpointer the server persists ServerState (which
        # carries the round counter the RNG folding derives from) every
        # ``checkpoint_every`` closed rounds, and a restarted rank 0
        # resumes from the last completed round instead of round 0.
        self._ckpt = checkpointer
        self.checkpoint_every = checkpoint_every
        self.resumed_from = 0
        # -- Byzantine defense plane (docs/FAULT_TOLERANCE.md "Threat
        # model"): the per-round defense rule rides cfg.fed.robust_*
        # through server_update; the cross-round reputation tracker
        # accumulates anomaly scores and quarantines repeat offenders —
        # excluded from aggregation but still served, so a false
        # positive can earn its way back. Its state persists through
        # the round checkpointer below: a restarted server does not
        # forget who it banned.
        self._pipeline = robust.DefensePipeline.from_fed(cfg.fed)
        # surface the contradiction at construction, before the
        # readiness barrier — not at the first round close, where a
        # supervised deployment would crash-loop its restart budget
        robust.check_fednova_compat(cfg.fed.algorithm,
                                    self._pipeline.method)
        self._quarantine = quarantine or QuarantinePolicy()
        self._reputation = ReputationTracker(size, self._quarantine)
        self._diag_fn = None  # lazily-jitted anomaly scorer
        # -- shape-bucketed compiled rounds (core/elastic.py): with
        # cfg.fed.elastic_buckets the aggregation pass is compiled once
        # per power-of-two bucket (cohort padded with zero-weight /
        # zero-delta rows every defense rule masks out) and held in an
        # LRU of executables — membership churn costs a cache hit, not
        # an XLA recompile. Off by default: the eager aggregation path
        # below stays byte-identical to its pre-elastic self.
        self._elastic = bool(cfg.fed.elastic_buckets)
        # NOTE deliberately NOT donated: on the CPU backend
        # ``np.asarray`` of a jax array is zero-copy, so the
        # ``_round_sync`` host snapshot a mid-round WELCOME replays can
        # ALIAS the live ServerState buffers — donating the state would
        # let the compiled update overwrite the snapshot under a
        # concurrent rejoin (the same aliasing class PR 1's checkpoint
        # zero-copy SIGSEGV fix documents). The sim round donates
        # instead, where the state has exactly one owner.
        self._agg_cache = (
            E.CompiledRoundCache(self._bucketed_update,
                                 family="deploy_update")
            if self._elastic else None
        )
        self._diag_cache = (
            E.CompiledRoundCache(self._bucketed_diag,
                                 family="deploy_diag")
            if self._elastic else None
        )
        # -- compressed weight-update wire (core/compress.py,
        # docs/PERFORMANCE.md "Wire compression"): clients ship typed
        # quantized/sparsified delta payloads instead of dense
        # variables; the server validates them at the receive edge,
        # stores the (small) payloads, and decompresses the stacked
        # round inside a compiled — optionally client-axis-sharded —
        # program at close. Off by default: the dense path is
        # byte-identical on the wire and in here.
        self._cspec = CMP.CompressionSpec.from_fed(cfg.fed,
                                                   seed=cfg.seed)
        self._payload_template = (
            CMP.payload_template(self._cspec, self.state.variables)
            if self._cspec.enabled() else None
        )
        if self._cspec.enabled():
            telemetry.METRICS.gauge(
                "compress.ratio",
                CMP.wire_ratio(self._cspec, self.state.variables),
            )
        self._decomp_cache = (
            E.CompiledRoundCache(self._decompress_prog,
                                 family="deploy_decompress")
            if self._cspec.enabled() else None
        )
        # memory-plane knobs (core/memscope.py): the monitor samples at
        # every round close below; --mem_headroom_warn tunes its alarm
        MEMSCOPE.MONITOR.headroom_warn = float(
            getattr(cfg.fed, "mem_headroom_warn", 0.9) or 0.9
        )
        # -- mesh-sharded server update (parallel/sharded_agg.py,
        # ROADMAP item 2): shard decompress -> clip -> defense-reduce
        # -> optimizer step over the client axis of a mesh spanning
        # this host's devices, all-gathering only the final params.
        # Off by default: the replicated paths above stay untouched.
        self._sharded = None
        if cfg.fed.shard_aggregation:
            from fedml_tpu.parallel.sharded_agg import ShardedAggregator

            self._sharded = ShardedAggregator(
                cfg, self.steps_per_epoch, self.batch_size,
                spec=self._cspec,
            )
        if checkpointer is not None:
            if checkpoint_every < 1:
                raise ValueError(
                    f"checkpoint_every must be >= 1 with a checkpointer, "
                    f"got {checkpoint_every}"
                )
            from fedml_tpu.utils.checkpoint import from_savable

            raw, start = checkpointer.restore_raw()
            if raw is not None:
                if isinstance(raw, dict) and "server" in raw:
                    # composite payload (PR 4+): server state + the
                    # reputation plane, + the membership ledger once
                    # the world went elastic. Reputation/membership
                    # arrays adapt to a DIFFERENT relaunch world size
                    # — the checkpoint is authoritative.
                    self.state = from_savable(self.state, raw["server"])
                    self._reputation.load_arrays(raw["reputation"])
                    if "membership" in raw:
                        self._ledger.load_arrays(raw["membership"])
                else:
                    # checkpoint written before the reputation plane:
                    # a bare ServerState. Restore it and start with a
                    # clean reputation — an upgraded server must
                    # resume, not crash-loop the Supervisor's restart
                    # budget away.
                    self.state = from_savable(self.state, raw)
                    import warnings

                    warnings.warn(
                        "restored a pre-reputation checkpoint (bare "
                        "ServerState); quarantine state starts fresh",
                        stacklevel=2,
                    )
            if start:
                if int(self.state.round) != start:
                    raise ValueError(
                        f"checkpoint at step {start - 1} carries "
                        f"round={int(self.state.round)}; expected "
                        f"{start} — wrong run directory?"
                    )
                self.round_idx = start
                self.resumed_from = start
                telemetry.METRICS.inc("recovery.resumes")
                telemetry.METRICS.gauge("recovery.resumed_from_round",
                                        start)
                telemetry.RECORDER.record("resume", round=start)
        self.register_message_receive_handler(
            MSG_TYPE_C2S_RESULT, self._handle_result
        )
        # library-path membership entries; the deployment barrier
        # re-registers JOIN with its pre-kickoff-aware wrapper
        # (deploy.py)
        self.register_message_receive_handler(
            MSG_TYPE_C2S_JOIN, lambda msg: self.on_peer_join(msg.sender)
        )
        self.register_message_receive_handler(
            MSG_TYPE_C2S_LEAVE,
            lambda msg: self.on_peer_leave(msg.sender),
        )
        # live run introspection (core/export.py ``/statusz``): the
        # actor is a WEAKLY-held status source — registration costs
        # nothing while the exporter is off, and a dead actor is
        # pruned at snapshot time instead of being kept alive
        EXPORT.register_status_source("server", self)

    def status(self) -> dict:
        """One ``/statusz`` snapshot: scalars copied under the
        existing round lock (briefly), membership/quarantine read from
        their own thread-safe planes — no new lock is held across
        serialization (the HTTP handler json-encodes the returned
        plain dict outside every lock)."""
        with self._lock:
            pending = len(self._results)
            dead = sorted(self.dead_peers)
            failure = self.failure
            round_idx = self.round_idx
        mem = self._ledger.summary()
        return {
            "actor": type(self).__name__,
            "round": round_idx,
            "num_rounds": self.cfg.fed.num_rounds,
            "results_pending": pending,
            "membership": {k: len(v) for k, v in mem.items()},
            "quarantined": self._reputation.quarantined(),
            "dead_peers": dead,
            "resumed_from": self.resumed_from,
            "done": self.done.is_set(),
            "failure": failure,
        }

    @property
    def variables(self):
        return self.state.variables

    def _sample(self) -> np.ndarray:
        """Seeded cohort sampling (reference ``client_sampling``,
        ``FedAVGAggregator.py:90-98``). In the distributed path the cohort
        size is the worker count, as in the reference (one MPI rank per
        sampled client, ``FedAvgAPI.py:36-66``); if there are more workers
        than clients the assignment wraps so every worker gets a client.
        The worker count is the CURRENT membership (elastic worlds grow
        and shrink it); in a static world it equals the launch
        ``size - 1`` and the draw is unchanged."""
        n_workers = max(1, len(self._member_workers()))
        if n_workers >= self.num_clients:
            return np.arange(self.num_clients)
        rng = np.random.default_rng(self.round_idx)
        return rng.choice(self.num_clients, n_workers, replace=False)

    # -- straggler accounting (all under self._lock) -----------------------

    def client_ranks(self) -> list[int]:
        """Every currently-ACTIVE member (broadcast / FINISH targets) —
        including admissions whose first round is still ahead, and
        excluding departed ranks."""
        return self._ledger.active_ranks()

    def _member_workers(self) -> list[int]:
        """Members participating in the CURRENT round: ACTIVE, and
        admitted at or before this round's boundary (a mid-round
        admission must not raise the in-flight round's quorum bar for a
        sync it never received)."""
        return self._ledger.active_ranks(self.round_idx)

    def _live_workers(self) -> list[int]:
        return [
            r for r in self._member_workers()
            if r not in self.dead_peers
        ]

    def _quorum(self) -> int:
        """Results required to close the round at the deadline: a
        fraction of the CURRENTLY live workers, never below 1 (a death
        detected mid-round shrinks the quorum with the cohort)."""
        live = len(self._live_workers())
        return max(1, math.ceil(self.round_policy.quorum_fraction * live))

    def kickoff(self) -> None:
        """Deployment-barrier entry: start the (possibly resumed) run
        unless a round is already underway. After a server restart the
        barrier can complete on the very message that closed the
        resumed round (the Manager's handler runs before the barrier
        observer), whose ``_close_round`` already started the next one
        — a second ``start_round`` here would re-broadcast it and make
        every client compute the round twice."""
        with self._lock:
            sync = self._round_sync
            if sync is not None and sync[0] == self.round_idx:
                return  # a round is already in flight
        self.start_round()

    def start_round(self) -> None:
        # a server restored from its FINAL checkpoint has nothing left
        # to run — finish immediately instead of broadcasting a sync
        # for a round past the end
        if self.round_idx >= self.cfg.fed.num_rounds:
            self.done.set()
            self.finish_all()
            return
        # reconcile rejoin/death races at the round boundary: a rank in
        # dead_peers that the liveness monitor does NOT consider dead
        # was revived by a JOIN that interleaved with an in-flight
        # death callback (the callback re-added it after the rejoin's
        # removal, stranding a live rank outside the cohort forever).
        # The monitor is the live-ness source of truth — a truly-down
        # peer lands (back) in monitor.dead within a heartbeat timeout,
        # so healing here converges instead of flapping.
        mon = self.liveness
        if mon is not None:
            mon_dead = mon.dead_snapshot()
            with self._lock:
                stranded = sorted(self.dead_peers - mon_dead)
                self.dead_peers -= set(stranded)
            if stranded:
                telemetry.METRICS.inc("recovery.rejoins_reconciled",
                                      len(stranded))
                telemetry.RECORDER.record(
                    "rejoin_reconciled", peers=stranded,
                    round=self.round_idx,
                )
        cohort = self._sample()
        self._round_t0 = time.monotonic()
        if ANATOMY.enabled:
            # the anatomy plane (core/anatomy.py): every deploy
            # timestamp below is passed explicitly on the actor's own
            # monotonic clock, so arrivals and the sync origin compare
            ANATOMY.begin_round(self.round_idx, path="deploy")
        tr = telemetry.TRACER
        if tr is not None:
            # one trace id per round: every sync this broadcast ships
            # (and every result it provokes) correlates under it
            telemetry.set_current_trace(telemetry.new_trace_id())
            tr.log_round_start(self.round_idx)
        host_vars = jax.tree.map(np.asarray, self.variables)
        # slot = the rank's position among this round's MEMBER workers:
        # in a static launch world that is exactly rank-1 (the historic
        # assignment); in an elastic world it stays dense as ranks
        # beyond the launch world join and others leave
        slots = {r: i for i, r in enumerate(self._member_workers())}
        with self._lock:
            ranks = self._live_workers()
            self._extensions_used = 0
            self._deadline_gen += 1
            gen = self._deadline_gen
            # one consistent (round, model, cohort, slots) snapshot:
            # WELCOME replies to mid-round rejoiners replay exactly
            # this sync
            self._round_sync = (self.round_idx, host_vars, cohort, slots)
        self.broadcast(
            MSG_TYPE_S2C_SYNC_MODEL,
            lambda r: {
                KEY_MODEL_PARAMS: host_vars,
                KEY_CLIENT_INDEX: int(
                    cohort[slots.get(r, r - 1) % len(cohort)]
                ),
                KEY_ROUND: self.round_idx,
            },
            ranks=ranks,
            on_send_error=self._on_sync_send_failed,
        )
        if self.round_policy.round_deadline_s is not None:
            t = threading.Timer(
                self.round_policy.round_deadline_s,
                self._on_round_deadline,
                args=(self.round_idx, gen),
            )
            t.daemon = True
            self._deadline_timer = t
            t.start()

    def _on_sync_send_failed(self, rank: int, err: Exception) -> None:
        """A model sync that cannot be shipped == a crashed worker; the
        round proceeds without it rather than aborting the broadcast."""
        self.on_peer_dead(rank)

    def on_peer_join(self, rank: int) -> str | None:
        """Unified JOIN entry (docs/FAULT_TOLERANCE.md "Elastic
        membership"): dispatches on the membership ledger's verdict —

        - an ACTIVE member's JOIN is the crash-recovery REJOIN
          (:meth:`on_peer_rejoin`, unchanged) — unless its admission
          has not taken effect yet (a just-admitted rank's announce
          loop re-sends JOIN until the next round's sync arrives; a
          WELCOME now would pull it into the CURRENT round, whose
          quorum and cohort slots were fixed without it);
        - an unknown or previously-LEFT rank is ADMITTED: stable client
          id assigned, liveness armed, first cohort slot at the next
          round boundary — or THIS round's, when no round is in flight
          yet (a restored all-departed world admitting its next member
          pre-kickoff must serve it in the round it is about to
          broadcast, not one past it);
        - an EVICTED rank is rejected silently — never ACKed, so the
          banned client's announce loop times out loudly on its side
          instead of idling against a world that will not serve it.
        """
        with self._lock:
            if self.done.is_set() or self.failure is not None:
                return None
            if not self._elastic and self._ledger.status(rank) is None:
                # static world, never-seen rank: drop un-ACKed — the
                # pre-elastic contract (run.py's client-side guard
                # says "a static server drops it", and admitting here
                # would shift every member's cohort slot in a world
                # the operator configured as fixed)
                telemetry.METRICS.inc("membership.rejected_joins")
                return None
            sync = self._round_sync
            in_flight = sync is not None and sync[0] == self.round_idx
            verdict = self._ledger.admit(rank, self.round_idx,
                                         immediate=not in_flight)
            effective = rank in self._ledger.active_ranks(self.round_idx)
        if verdict == "rejected":
            return verdict
        if verdict == "member":
            if effective:
                self.on_peer_rejoin(rank)
            return verdict
        # newly admitted (or returning after a graceful LEAVE): a crash
        # while LEFT is impossible, so there is no dead-peer state to
        # reverse — just arm liveness and grow the per-rank planes
        self._reputation.ensure_size(rank + 1)
        with self._lock:
            self.dead_peers.discard(rank)
        if self.liveness is not None:
            self.liveness.watch(rank)
        return verdict

    def on_peer_leave(self, rank: int) -> None:
        """Graceful departure (``MSG_TYPE_C2S_LEAVE``): the rank is
        marked LEFT in the ledger — NOT dead. No restart budget is
        spent, no dead-peer flight dump fires, and its reputation is
        frozen, not laundered (a later rejoin resumes the same score).
        A result it already submitted this round stays valid (it
        contributed, then left). The round re-evaluates its close
        condition immediately: the departed rank no longer counts
        toward quorum."""
        left = self._ledger.leave(rank, self.round_idx)
        if not left:
            return
        if self.liveness is not None:
            self.liveness.unwatch(rank)
        with self._lock:
            self.dead_peers.discard(rank)
            self._welcomed.pop(rank, None)
        self._maybe_close_round(deadline_fired=False)

    def evict_rank(self, rank: int, notify: bool = True) -> None:
        """Permanent eviction: future JOINs from this rank are rejected
        (the one transition nothing undoes short of a fresh run dir).
        Used by operators via the library API and by the quarantine
        plane's ``evict_after`` policy. ``notify=False`` skips the
        FINISH to the banned rank — the restart replay path uses it,
        where the rank's process already exited and a send would only
        sit out the transport's full retry budget."""
        self._ledger.evict(rank, self.round_idx)
        if self.liveness is not None:
            self.liveness.unwatch(rank)
        with self._lock:
            self.dead_peers.discard(rank)
            self._results.pop(rank, None)
            self._welcomed.pop(rank, None)
        # tell the banned rank to wind down cleanly: under a supervisor
        # an evicted client left idling would otherwise crash-loop its
        # restart budget (its JOINs are never ACKed) and take the whole
        # world down with it — a FINISH carrying the reason lets it
        # exit 0 with status "evicted", which the Supervisor treats
        # like a graceful LEAVE (gone by design, never respawned)
        if notify:
            try:
                self.send_message(Message(
                    MSG_TYPE_FINISH, self.rank, rank,
                    {"reason": "evicted"},
                ))
            except Exception:
                pass  # peer unreachable; announce loop times out loudly
        self._maybe_close_round(deadline_fired=False)

    def on_peer_rejoin(self, rank: int) -> None:
        """Rejoin entry (``MSG_TYPE_C2S_JOIN`` mid-run, docs/
        FAULT_TOLERANCE.md "Recovery"): reverse the dead-peer removal,
        re-arm the rank's liveness watchdog, and reply ``WELCOME`` with
        the CURRENT round's sync payload — the same (model, round,
        client assignment) its cohort-mates received, so a rejoiner's
        result is byte-identical to the one the original sync would
        have produced. Safe from any thread; a duplicate JOIN from an
        already-live rank only refreshes its watchdog (the duplicate
        result its WELCOME provokes is discarded by the keep-first
        dedup)."""
        with self._lock:
            if self.done.is_set() or self.failure is not None:
                return
            was_dead = rank in self.dead_peers
            self.dead_peers.discard(rank)
            sync = self._round_sync
            if sync is not None and sync[0] != self.round_idx:
                # the snapshot's round is mid-close (round_idx already
                # advanced): a WELCOME for it would only provoke a
                # local update whose result is guaranteed stale —
                # skip; the rank is live again, so the imminent
                # start_round broadcast covers it
                sync = None
            if sync is not None:
                if not was_dead and self._welcomed.get(rank) == sync[0]:
                    # duplicate announce (the WELCOME is still in
                    # flight): refresh the watchdog, send nothing
                    sync = None
                    duplicate = True
                else:
                    self._welcomed[rank] = sync[0]
                    duplicate = False
            else:
                duplicate = not was_dead
        if self.liveness is not None:
            self.liveness.revive(rank)
        if duplicate:
            return
        telemetry.METRICS.inc("recovery.rejoins")
        telemetry.RECORDER.record("rejoin", peer=rank, was_dead=was_dead)
        if sync is None:
            return  # no round underway; the next broadcast covers it
        round_idx, host_vars, cohort, slots = sync
        try:
            self.send_message(
                Message(
                    MSG_TYPE_S2C_WELCOME,
                    self.rank,
                    rank,
                    {
                        KEY_MODEL_PARAMS: host_vars,
                        KEY_CLIENT_INDEX: int(
                            cohort[slots.get(rank, rank - 1)
                                   % len(cohort)]
                        ),
                        KEY_ROUND: round_idx,
                    },
                )
            )
        except Exception:
            self.on_peer_dead(rank)  # flapped again mid-welcome

    def on_peer_dead(self, rank: int) -> None:
        """Dead-peer callback (heartbeat monitor / failed sends). Safe to
        call from any thread, idempotent per rank."""
        with self._lock:
            if rank in self.dead_peers or self.done.is_set():
                return
            self.dead_peers.add(rank)
            self._results.pop(rank, None)  # a dead rank's result is void
            dead = sorted(self.dead_peers)  # snapshot under the lock
        telemetry.METRICS.inc("round.dead_peers")
        # a dead worker is a flight-recorder trigger: the artifact names
        # the peer and carries the recent event ring + metrics snapshot
        telemetry.flight_dump(
            "dead_peer", peer=rank, round=self.round_idx,
            dead_peers=dead,
        )
        self._maybe_close_round(deadline_fired=False)

    def _on_round_deadline(self, round_idx: int, gen: int) -> None:
        self._maybe_close_round(deadline_fired=True,
                                deadline_round=round_idx,
                                deadline_gen=gen)

    def _abort_locked(self, why: str) -> None:
        """Record the abort decision. Must run under ``self._lock`` so a
        straggler result racing the deadline cannot both close the round
        and see the run aborted; the FINISH broadcast happens after the
        lock is released (it takes no shared state)."""
        self.failure = why

    def _maybe_close_round(
        self,
        deadline_fired: bool,
        deadline_round: int | None = None,
        deadline_gen: int | None = None,
    ) -> None:
        """Close the round if its exit condition holds: every live worker
        reported (zero-fault path — byte-identical to the strict
        behavior), or the deadline fired with >= quorum results. Aborts
        when no live worker remains or the deadline passes under quorum.
        The round index advances under the SAME lock that claims the
        result set, so a result racing the close is correctly classified
        as a stale straggler rather than leaking into the next round; a
        deadline timer carries its own round (``deadline_round``) and is
        re-validated under that lock, so a timer firing just as its round
        closes cannot apply deadline semantics to the NEXT round."""
        extended = None
        with self._lock:
            if self.done.is_set() or self.failure is not None:
                return
            if deadline_round is not None and (
                deadline_round != self.round_idx
                or (deadline_gen is not None
                    and deadline_gen != self._deadline_gen)
            ):
                # stale timer: its round already closed, or a recovery
                # extension superseded it (cancel() cannot stop a timer
                # whose callback is already blocked on this lock)
                return
            live = self._live_workers()
            n_results = len(self._results)
            # the fast-path close means "every LIVE worker reported":
            # a graceful leaver's booked result stays valid for quorum
            # and aggregation, but must not stand in for a still-
            # computing live member's
            n_live_results = sum(1 for r in live if r in self._results)
            quorum = self._quorum()
            abort = results = None
            closed_idx = self.round_idx
            dead = sorted(self.dead_peers)  # snapshot under the lock
            if live and (n_live_results >= len(live) or (
                deadline_fired and n_results >= quorum
            )):
                results, self._results = self._results, {}
                self.round_idx += 1
                if self._deadline_timer is not None:
                    self._deadline_timer.cancel()
                    self._deadline_timer = None
            elif deadline_fired or not live:
                sync = self._round_sync
                if not deadline_fired and (
                        sync is None or sync[0] != self.round_idx):
                    # no-live-workers check with NO round in flight: a
                    # restored server replaying presumed departures
                    # before kickoff (every member departed by design).
                    # There is nothing to abort — the ready barrier is
                    # waiting for the next admission to BE the world
                    return
                # under quorum (or out of workers entirely): abort only
                # after recovery is exhausted — each extension re-arms
                # the deadline so a supervised restart can rejoin and
                # deliver the missing results
                if (self._extensions_used
                        < self.round_policy.recovery_extensions
                        and self.round_policy.round_deadline_s
                        is not None):
                    self._extensions_used += 1
                    extended = self._extensions_used
                    # supersede the timer already covering this round
                    # (the all-dead path gets here with the ORIGINAL
                    # deadline timer still armed — left valid it would
                    # fire at the unextended time, see extensions
                    # exhausted, and abort inside the window the
                    # extension opened). cancel() handles the not-yet-
                    # fired case; the generation bump invalidates a
                    # timer already blocked on this lock.
                    if self._deadline_timer is not None:
                        self._deadline_timer.cancel()
                    self._deadline_gen += 1
                    t = threading.Timer(
                        self.round_policy.round_deadline_s,
                        self._on_round_deadline,
                        args=(self.round_idx, self._deadline_gen),
                    )
                    t.daemon = True
                    self._deadline_timer = t
                    t.start()
                elif not live:
                    spent = (
                        f" ({self._extensions_used} recovery "
                        f"extensions spent)"
                        if self.round_policy.recovery_extensions
                        else ""
                    )
                    # the MEMBER count, not the launch world: an
                    # elastic run may have grown/shrunk — and "no live
                    # workers" covers graceful departures too, not just
                    # deaths
                    abort = (
                        f"no live workers left before round "
                        f"{self.round_idx} closed "
                        f"({len(self._member_workers())} members, "
                        f"dead peers {sorted(self.dead_peers)}{spent})"
                    )
                else:
                    abort = (
                        f"round {self.round_idx} deadline "
                        f"({self.round_policy.round_deadline_s}s) "
                        f"expired with {n_results}/{len(live)} live "
                        f"results (quorum {quorum}; dead peers "
                        f"{sorted(self.dead_peers)}; "
                        f"{self._extensions_used} recovery extensions "
                        f"spent)"
                    )
            else:
                return  # stragglers may still arrive before the deadline
            if abort is not None:
                self._abort_locked(abort)
        if extended is not None:
            telemetry.METRICS.inc("recovery.deadline_extensions")
            telemetry.RECORDER.record(
                "deadline_extended", round=closed_idx,
                extension=extended, results=n_results, quorum=quorum,
            )
            return
        if abort is not None:
            # a quorum-lost abort is a flight-recorder trigger: PR 1
            # made it loud, this makes it debuggable
            telemetry.METRICS.inc("round.quorum_lost_aborts")
            telemetry.flight_dump(
                "quorum_lost", detail=abort, round=closed_idx,
                dead_peers=dead,
            )
            self.finish_all()  # done unset: deploy raises the diagnostic
        else:
            self._close_round(results, closed_idx, n_live=len(live),
                              dead=dead)

    def _discard_locked(self, msg: Message) -> bool:
        """Cheap drop checks, under ``self._lock``: finished/aborted
        run, stale round tag (a straggler's result from an already-
        closed round must not leak into the current aggregate; untagged
        results predate round-tagging and are accepted for
        compatibility), dead sender, and duplicate ``(round, rank)``
        results — chaos dup / retry resend / rejoin recompute — where
        the FIRST is kept so sample mass is never double-counted in the
        renormalized survivor aggregation."""
        if self.done.is_set() or self.failure is not None:
            return True
        msg_round = msg.get(KEY_ROUND)
        if msg_round is not None and int(msg_round) != self.round_idx:
            telemetry.METRICS.inc("round.stale_results")
            return True
        if msg.sender in self.dead_peers:
            return True  # declared dead; its late result is void
        if self._ledger.status(msg.sender) == "evicted":
            # evict_rank voided this rank's pending result; a copy
            # still in flight must not be re-accepted into the round
            # (a LEFT rank's result stays valid — it contributed,
            # then departed — but a BAN is authoritative)
            return True
        if msg.sender in self._results:
            telemetry.METRICS.inc("round.duplicate_results")
            return True
        return False

    def _screen_compressed(self, msg: Message):
        """Receive-edge screen for a compressed result: the typed
        payload must match the spec's expected structure (codec tag,
        per-leaf shapes/dtypes, in-range top-k indices) and carry only
        finite floats — a malformed or poisoned payload is counted
        ``compress.decode_errors`` and dropped, never stacked into the
        compiled decompress. Returns the payload or None."""
        comp = msg.get(KEY_COMPRESSED)
        err = None
        if not isinstance(comp, dict) or "payload" not in comp:
            err = (
                "dense result on a compressed wire"
                if msg.get(KEY_MODEL_PARAMS) is not None
                else "missing compressed payload"
            )
        elif comp.get("codec") != self._cspec.method:
            err = (
                f"codec {comp.get('codec')!r} != configured "
                f"{self._cspec.method!r}"
            )
        else:
            err = CMP.validate_payload(self._payload_template,
                                       comp["payload"])
        if err is not None:
            telemetry.METRICS.inc("compress.decode_errors")
            telemetry.RECORDER.record(
                "compress_decode_error", peer=msg.sender,
                round=msg.get(KEY_ROUND), detail=err,
            )
            return None
        return comp["payload"]

    def _handle_result(self, msg: Message) -> None:
        # cheap checks FIRST: a duplicate or post-close straggler must
        # not pay the full-pytree scan below
        with self._lock:
            if self._discard_locked(msg):
                return
        n_k = float(msg.get(KEY_NUM_SAMPLES))
        if self._cspec.enabled():
            params = self._screen_compressed(msg)
            if params is None:
                return
            if not math.isfinite(n_k):
                # mirror the dense screen's accounting: a poisoned
                # sample count must be as visible on the compressed
                # wire as on the dense one
                telemetry.METRICS.inc("robust.nonfinite_rejected")
                telemetry.RECORDER.record(
                    "nonfinite_rejected", peer=msg.sender,
                    round=msg.get(KEY_ROUND),
                )
                return
        else:
            params = msg.get(KEY_MODEL_PARAMS)
            if params is None:
                # a compressed result against a dense-configured
                # server (config skew between ranks): unusable
                telemetry.METRICS.inc("compress.decode_errors")
                telemetry.RECORDER.record(
                    "compress_decode_error", peer=msg.sender,
                    round=msg.get(KEY_ROUND),
                    detail="compressed result on a dense wire",
                )
                return
            # non-finite screening (outside the lock — it touches
            # every leaf): a single NaN/Inf delta defeats the weighted
            # mean AND norm-clip (NaN * 0-scale is still NaN), so a
            # poisoned result never enters the aggregate. The screened
            # rank stays live and simply has no result this round — it
            # counts against quorum like a straggler.
            if not _result_is_finite(params, n_k):
                telemetry.METRICS.inc("robust.nonfinite_rejected")
                telemetry.RECORDER.record(
                    "nonfinite_rejected", peer=msg.sender,
                    round=msg.get(KEY_ROUND),
                )
                return
        with self._lock:
            # re-validate: the round can close, or the sender can die
            # or deliver via another path, while the scan ran unlocked
            if self._discard_locked(msg):
                return
            self._results[msg.sender] = (params, n_k)
        if ANATOMY.enabled:
            # straggler attribution (core/anatomy.py): first ACCEPTED
            # result per rank, on the same monotonic clock as
            # _round_t0 — screened/duplicate results never count
            ANATOMY.note_arrival(msg.sender, ts=time.monotonic())
        self._maybe_close_round(deadline_fired=False)

    @property
    def quarantined_ranks(self) -> list[int]:
        return self._reputation.quarantined()

    @property
    def membership(self) -> dict:
        """Rank lists per membership status (run-summary view)."""
        return self._ledger.summary()

    def _bucketed_update(self, state, stacked_vars, n_k, valid, rkey):
        """The bucket-compiled aggregation body: exactly the eager
        path's ``server_update`` with the padding mask threaded through
        (zero-weight, zero-delta pad rows cannot perturb any rule —
        core/elastic.py)."""
        return server_update(
            self.cfg.fed,
            self.cfg.train,
            self.steps_per_epoch,
            self.batch_size,
            state,
            stacked_vars,
            n_k,
            rkey,
            local_reducer(),
            valid=valid,
        )

    def _decompress_prog(self, stacked_payload, gvars):
        """Bucket-compiled decompress: stacked payloads -> stacked
        dense VARIABLES (``global + delta``). A padded zero payload
        row decompresses to a delta of exactly zero — the healed-row
        convention every downstream mask-aware rule expects."""
        delta = CMP.decompress_stacked(self._cspec, stacked_payload,
                                       gvars)
        return jax.tree.map(
            lambda g, d: (g[None] + d).astype(g.dtype), gvars, delta
        )

    def _decompress_results(
        self, results: dict[int, tuple[dict, float]]
    ) -> dict:
        """Inflate one closed round's compressed payloads into dense
        variables through ONE compiled decompress over the stacked
        round — client-axis-sharded when the mesh is on, bucket-padded
        so membership churn stays a compile-cache hit. Returns the
        dense stacked tree in sorted-rank order; downstream
        (reputation scoring, aggregation) consumes the STACK directly
        — rows are sliced out only on the rare quarantine-exclusion
        path."""
        ranks = sorted(results)
        stacked = T.tree_stack([
            jax.tree.map(jnp.asarray, results[r][0]) for r in ranks
        ])
        n = len(ranks)
        if self._sharded is not None:
            return self._sharded.decompress(stacked,
                                            self.state.variables, n)
        bucket = E.bucket_for(n) if self._elastic else n
        padded = CMP.pad_stacked_payload(stacked, bucket)
        dense = self._decomp_cache(bucket, padded,
                                   self.state.variables)
        return jax.tree.map(lambda x: x[:n], dense)

    @staticmethod
    def _bucketed_diag(stacked_params, gp, valid):
        deltas = jax.tree.map(
            lambda s, g: s - g[None], stacked_params, gp
        )
        return robust.anomaly_scores(deltas, valid)

    def _diagnose(self, stacked_vars,
                  n_rows: int | None = None) -> dict[str, np.ndarray]:
        """Per-client anomaly scores over this round's results (one
        jitted flatten + gram matmul, core/robust.anomaly_scores).
        Static path: recompiles per distinct result count, which a
        quorum-shrunk round changes rarely. Elastic path
        (``n_rows``): the stack is padded to its bucket and scored by
        a bucket-compiled executable, so membership churn never
        retraces the scorer; rows past ``n_rows`` are padding debris
        and are sliced off before anything host-side sees them."""
        gp = self.state.variables["params"]
        if self._elastic and n_rows is not None:
            bucket = E.bucket_for(n_rows)
            padded, _, valid = E.pad_stacked(
                stacked_vars["params"],
                np.ones((n_rows,), np.float32),
                gp,
                bucket,
            )
            out = self._diag_cache(bucket, padded, gp, valid)
            return {k: np.asarray(v)[:n_rows] for k, v in out.items()}
        if self._diag_fn is None:
            # same pipeline as the bucketed scorer, no padding mask
            # (anomaly_scores treats valid=None as all-valid)
            self._diag_fn = jax.jit(
                lambda s, gp: self._bucketed_diag(s, gp, None)
            )
        out = self._diag_fn(stacked_vars["params"], gp)
        return {k: np.asarray(v) for k, v in out.items()}

    def _score_and_exclude(
        self, results: dict[int, tuple[dict, float]], closed_idx: int,
        stacked_all: dict | None = None,
    ) -> tuple[list[int], dict | None]:
        """The reputation pass over one closed round's results: score
        every reporter, fold into the cross-round tracker, and return
        ``(included ranks, stacked tree or None)`` — the stack built
        for scoring rides back to the caller when every reporter
        survived, so the cohort's params cross to device ONCE per
        round, not once for scoring and again for aggregation.
        Quarantined reporters are scored (they can earn their way
        back) but excluded. Skipped entirely on the zero-defense path
        (mean rule, no quarantine, metrics off), which therefore pays
        nothing."""
        ranks = sorted(results)
        m = telemetry.METRICS
        score_now = self._quarantine.enabled() or (
            self._pipeline.method != "mean" and m.enabled
        )
        if not score_now or not ranks:
            # the caller may already hold the stacked round (the
            # compressed path's decompress output) — pass it back so
            # it is never rebuilt from rows
            return ranks, stacked_all
        self._reputation.ensure_size(max(ranks) + 1)
        if stacked_all is None:
            stacked_all = T.tree_stack([results[r][0] for r in ranks])
        diag = self._diagnose(stacked_all, len(ranks))
        events = self._reputation.observe(closed_idx, ranks,
                                          diag["score"])
        if self._quarantine.evict_after > 0:
            # quarantine -> eviction escalation: a rank that has sat in
            # quarantine for evict_after FULL rounds without earning
            # release is permanently banned (docs/FAULT_TOLERANCE.md
            # "Elastic membership"). Strictly more than: the round that
            # TRIPPED the quarantine (closed_idx == q_at) is not a
            # round "sat without release" — evict_after=1 promises one
            # recoverable round, not an instant ban
            for r in list(self._reputation.quarantined()):
                q_at = int(self._reputation.quarantined_at[r])
                if (closed_idx - q_at >= self._quarantine.evict_after
                        and self._ledger.status(r) != "evicted"):
                    self.evict_rank(r)
        excluded = [r for r in ranks
                    if self._reputation.is_quarantined(r)]
        included = [r for r in ranks if r not in excluded]
        if not included:
            # every reporter is quarantined: refusing to aggregate
            # would stall the run forever — degrade to the full set
            # and let the per-round defense rule carry the round
            telemetry.RECORDER.record(
                "quarantine_overruled", round=closed_idx, ranks=ranks
            )
            included, excluded = ranks, []
        if m.enabled:
            if events["suspected"]:
                m.inc("defense.suspected", len(events["suspected"]))
            if events["quarantined"]:
                m.inc("defense.quarantines", len(events["quarantined"]))
            if events["released"]:
                m.inc("defense.releases", len(events["released"]))
            if excluded:
                m.inc("defense.excluded", len(excluded))
            sel_excluded = self._pipeline.excluded_count(len(included))
            if sel_excluded:
                # results the krum-family selection rule drops inside
                # the aggregation pass by construction
                m.inc("defense.excluded", sel_excluded)
            if self._pipeline.method == "fltrust":
                m.inc("defense.reweighted", len(included))
            m.gauge("defense.quarantined",
                    len(self._reputation.quarantined()))
            m.gauge("defense.anomaly_score_max",
                    float(diag["score"].max()))
            for r in ranks:
                # label-capped family: a 10k-client cohort folds ranks
                # beyond the cap into defense.score_rank.other instead
                # of growing the registry per peer
                m.gauge_labeled("defense.score_rank", str(r),
                                self._reputation.score(r), sep="")
        if events["released"]:
            telemetry.RECORDER.record(
                "quarantine_released", round=closed_idx,
                peers=events["released"],
            )
        if events["quarantined"]:
            # a quarantine trip is a flight-recorder trigger, like a
            # dead peer: the artifact names the peers and their scores
            telemetry.RECORDER.record(
                "quarantine", round=closed_idx,
                peers=events["quarantined"],
            )
            telemetry.flight_dump(
                "quarantine", round=closed_idx,
                peers=events["quarantined"],
                scores={r: self._reputation.score(r) for r in ranks},
                quarantined=self._reputation.quarantined(),
            )
        return included, (stacked_all if included == ranks else None)

    def _close_round(
        self,
        results: dict[int, tuple[dict, float]],
        closed_idx: int,
        n_live: int | None = None,
        dead: list[int] | None = None,
    ) -> None:
        """Aggregate ``results`` through the SAME server_update as the
        compiled sim (reference handle_message_receive_model_from_client,
        FedAvgServerManager.py:45-82 + fedopt/FedOptAggregator.py) — the
        two paths cannot drift. With a partial cohort the weighted mean
        renormalizes over the survivors' sample counts by construction.
        ``round_idx`` was already advanced by the caller under the lock;
        ``closed_idx`` is the round these results belong to."""
        tr = telemetry.TRACER
        if tr is not None:
            tr.log_round_end(closed_idx)
        anat = ANATOMY.enabled
        t_close = time.monotonic()
        if anat:
            # everything from sync broadcast to round close is client
            # compute + transport from the server's seat: `wire`
            ANATOMY.phase("wire", t_close - self._round_t0)
        m = telemetry.METRICS
        if m.enabled:
            wall = time.monotonic() - self._round_t0
            m.observe("round.wall_s", wall)
            # the SLO surface (core/slo.py, docs/OBSERVABILITY.md "Live
            # export and SLOs"): the deploy server shares the sims'
            # perf.round_wall_s histogram name, so one --slo spec
            # covers both drivers
            m.observe("perf.round_wall_s", wall)
            m.gauge("round.results", len(results))
            if n_live is not None and n_live > len(results):
                # live workers whose results the deadline cut out
                m.inc("round.stragglers", n_live - len(results))
            if len(results) < len(self._ledger.active_ranks(closed_idx)):
                # fewer results than the CLOSED round's members (the
                # elastic world's count, not the launch world_size —
                # round_idx has already advanced here): the weighted
                # mean below renormalizes over the survivors' sample
                # mass
                m.inc("round.quorum_renormalizations")
        telemetry.RECORDER.record(
            "round_close", round=closed_idx, results=len(results),
            dead_peers=dead if dead is not None else [],
        )
        t_agg0 = time.monotonic()
        stacked_all = None
        if self._cspec.enabled() and results:
            # inflate the round's compressed payloads first (ONE
            # compiled decompress over the stacked round; sharded over
            # the client axis when the mesh is on) — scoring and every
            # aggregation path below consume the dense STACK directly,
            # built exactly once; results keep the (small) payloads
            stacked_all = self._decompress_results(results)
        included, stacked = self._score_and_exclude(
            results, closed_idx, stacked_all
        )
        if stacked is None:
            if stacked_all is not None:
                # quarantine dropped ranks from a compressed round:
                # gather the kept rows out of the decompressed stack
                # (results still hold payloads, not dense rows)
                ranks = sorted(results)
                keep = jnp.asarray(
                    [ranks.index(r) for r in included], jnp.int32
                )
                stacked = jax.tree.map(lambda x: x[keep], stacked_all)
            else:
                stacked = T.tree_stack(
                    [results[r][0] for r in included]
                )
        weights = jnp.asarray([results[r][1] for r in included])
        t_def_end = time.monotonic() if anat else 0.0
        if anat:
            # decompress + robust scoring + stack build
            ANATOMY.phase("defense_agg", t_def_end - t_agg0)
        rkey = RND.round_key(self.root_key, self.state.round)
        if self._sharded is not None:
            # mesh-sharded update (parallel/sharded_agg.py): pads the
            # cohort to the mesh bucket itself and returns the new
            # replicated state — elastic or not, churn costs a
            # compile-cache hit in ITS executable LRU
            self.state = self._sharded.update(
                self.state, stacked, weights, rkey
            )
        elif self._elastic:
            # shape-bucketed aggregation (core/elastic.py): pad the
            # cohort to its power-of-two bucket and run the
            # bucket-compiled executable — a cohort-size change between
            # rounds (membership churn, quorum-shrunk closes) is a
            # compile-cache hit, not an XLA recompile
            bucket = E.bucket_for(len(included))
            padded, w, valid = E.pad_stacked(
                jax.tree.map(jnp.asarray, stacked), weights,
                self.variables, bucket,
            )
            self.state = self._agg_cache(
                bucket, self.state, padded, w, valid, rkey
            )
        else:
            self.state = server_update(
                self.cfg.fed,
                self.cfg.train,
                self.steps_per_epoch,
                self.batch_size,
                self.state,
                jax.tree.map(jnp.asarray, stacked),
                weights,
                rkey,
                local_reducer(),
            )
        agg_s = 0.0
        if m.enabled:
            # server-side device-time accounting (core/perf.py; the
            # accounting Smart-NIC FL serving work optimizes against,
            # arxiv 2307.06561): how much of the round the server's
            # chip actually worked vs sat waiting on the wire. The
            # block_until_ready makes agg time mean execution, not
            # dispatch — metrics-enabled runs only; the off path stays
            # async exactly as before.
            jax.block_until_ready(jax.tree.leaves(self.state.variables))
            agg_s = time.monotonic() - t_agg0
            if anat:
                # optimizer step + device wait, net of defense_agg
                ANATOMY.phase(
                    "server_update", time.monotonic() - t_def_end
                )
            wall_s = max(time.monotonic() - self._round_t0, 1e-9)
            m.observe("perf.agg_wall_s", agg_s)
            m.gauge("perf.host_wait_s", max(0.0, wall_s - agg_s))
            agg_frac = min(1.0, agg_s / wall_s)
            m.gauge("perf.agg_frac", agg_frac)
            if agg_frac < 0.005:
                # the deploy-path twin of the sims' dispatch-bound
                # detector: >99.5% of the round is client/transport
                # wait — the aggregator's device is idle-gapped
                m.inc("perf.idle_gap_rounds")
                if not self._idle_gap_flagged:
                    self._idle_gap_flagged = True
                    telemetry.RECORDER.record(
                        "perf_idle_gap", round=closed_idx,
                        agg_s=round(agg_s, 6), wall_s=round(wall_s, 6),
                        note="aggregation occupies <0.5% of the round; "
                             "the server device is idle waiting on "
                             "clients/transport",
                    )
            # round-close device-memory sample (core/memscope.py):
            # live/peak bytes + headroom gauges at the same boundary
            # the wall-time accounting uses
            MEMSCOPE.MONITOR.sample(tag=f"round{closed_idx}")
        t_ck = time.monotonic() if anat else 0.0
        if self._ckpt is not None and (
            (closed_idx + 1) % self.checkpoint_every == 0
            or closed_idx + 1 >= self.cfg.fed.num_rounds
        ):
            # atomic orbax save of the FULL ServerState — variables,
            # server-optimizer state, momentum, and the round counter
            # every RNG fold derives from — plus the reputation plane
            # (quarantine must survive a server SIGKILL) and the
            # membership ledger (a restarted server must serve the
            # grown/shrunk world, not the launch flag's), keyed by the
            # closed round, so a restart resumes here, not round 0
            self._ckpt.save(closed_idx, {
                "server": self.state,
                "reputation": self._reputation.state_arrays(),
                "membership": self._ledger.state_arrays(),
            })
            telemetry.METRICS.inc("recovery.checkpoints")
            telemetry.RECORDER.record("checkpoint", round=closed_idx)
            # counters ride the checkpoint cadence to disk: a SIGKILLed
            # server's metrics (rejoins, dedups, ...) survive the crash
            # instead of dying with the exit-time flush
            telemetry.flush_metrics()
            if anat:
                ANATOMY.phase("checkpoint", time.monotonic() - t_ck)
        if anat:
            # stragglers BEFORE end_round (end_round seals the ring
            # entry) and both BEFORE start_round below, which opens
            # the next round and clears the arrival table
            ANATOMY.attribute_stragglers(
                closed_idx, t_sync=self._round_t0, t_close=t_close,
                t_agg_s=agg_s,
            )
            ANATOMY.end_round(wall_s=time.monotonic() - self._round_t0)
        if self.on_round_done is not None:
            self.on_round_done(
                self.round_idx,
                {
                    "num_results": len(results),
                    "dead_peers": sorted(self.dead_peers),
                },
            )
        if self.round_idx >= self.cfg.fed.num_rounds:
            self.done.set()
            self.finish_all()
        else:
            self.start_round()


class FedAvgClientActor(ClientManager):
    """Rank>=1 worker (reference ``FedAVGClientManager`` +
    ``FedAVGTrainer``)."""

    def __init__(
        self,
        rank: int,
        size: int,
        transport: BaseTransport,
        model: FedModel,
        data: FederatedData,
        cfg: ExperimentConfig,
        leave_after_round: int | None = None,
    ):
        super().__init__(rank, size, transport)
        self.cfg = cfg
        self.model = model
        # elastic membership (docs/FAULT_TOLERANCE.md "Elastic
        # membership"): after submitting the result for this round the
        # client announces a GRACEFUL departure and winds down — the
        # server marks it LEFT (no dead-peer suspicion, no restart
        # budget), and a supervisor sees a clean exit
        self.leave_after_round = leave_after_round
        self.left = threading.Event()
        self.last_round = -1  # last round this rank worked (/statusz)
        self.arrays, batch = arrays_and_batch(data, cfg.data)
        max_n = self.arrays.max_client_samples
        task = make_task(data.task)
        self._local_update = jax.jit(
            build_local_update(model, task, cfg.train, batch, max_n)
        )
        self.root_key = jax.random.key(cfg.seed)
        # seeded Byzantine injection (core/adversary.py): when THIS
        # rank is a policy member it corrupts its own delta before
        # sending — the deploy-path mirror of the simulator's stacked
        # injection (docs/FAULT_TOLERANCE.md "Threat model")
        adv = cfg.adversary
        self._adversary = (
            adv
            if adv.enabled() and adv.is_member(rank, size - 1, base=1)
            else None
        )
        # -- compressed weight-update wire (core/compress.py): this
        # rank deltas its trained variables against the round's sync,
        # folds in the error-feedback residual it carries across
        # rounds, and ships the typed quantized/sparsified payload
        # instead of dense variables. Off by default (dense wire,
        # byte-identical).
        self._cspec = CMP.CompressionSpec.from_fed(cfg.fed,
                                                   seed=cfg.seed)
        self._residual = None  # lazy zero carry, shaped like variables
        self._compress_fn = None
        self._comp_cache: tuple[int, dict] | None = None
        if self._cspec.enabled():
            spec = self._cspec

            def _compress(delta, residual, key):
                payload, _, new_res = CMP.apply_with_feedback(
                    spec, delta, residual, key
                )
                return payload, new_res

            # the carried residual is donated: new carry aliases old
            self._compress_fn = jax.jit(_compress,
                                        donate_argnums=(1,))
        self.register_message_receive_handler(
            MSG_TYPE_S2C_SYNC_MODEL, self._handle_sync
        )
        # a WELCOME (rejoin reply) carries the same payload as the
        # round's sync and is worked identically — the server's
        # keep-first dedup absorbs the case where both arrive
        self.register_message_receive_handler(
            MSG_TYPE_S2C_WELCOME, self._handle_sync
        )
        EXPORT.register_status_source("client", self)

    def status(self) -> dict:
        """The client rank's ``/statusz`` contribution."""
        return {
            "actor": type(self).__name__,
            "rank": self.rank,
            "last_round": self.last_round,
            "left": self.left.is_set(),
        }

    def _compress_result(self, synced_vars, new_vars,
                         round_idx: int) -> dict:
        """Delta, fold in the error-feedback carry, compress, and
        advance the carry — ONCE per round: a duplicate sync for the
        same round (WELCOME racing the broadcast, chaos dup) re-sends
        the cached payload, and a delayed duplicate of an OLDER round
        — whose result the server's round-tag check is guaranteed to
        discard — is compressed against an empty carry WITHOUT
        touching the live residual (re-consuming it would mark its
        error as transmitted when the server never books it)."""
        if (self._comp_cache is not None
                and round_idx == self._comp_cache[0]):
            return self._comp_cache[1]
        key = CMP.slot_key(
            self._cspec,
            RND.round_key(self.root_key,
                          jnp.asarray(round_idx, jnp.int32)),
            self.rank - 1,
        )
        delta = jax.tree.map(jnp.subtract, new_vars, synced_vars)
        if (self._comp_cache is not None
                and round_idx < self._comp_cache[0]):
            payload = CMP.compress_tree(self._cspec, delta, key)
            return {
                "codec": self._cspec.method,
                "payload": jax.tree.map(np.asarray, payload),
            }
        if self._residual is None:
            self._residual = jax.tree.map(jnp.zeros_like, synced_vars)
        payload, self._residual = self._compress_fn(
            delta, self._residual, key
        )
        m = telemetry.METRICS
        if m.enabled:
            m.gauge("compress.residual_norm",
                    float(T.tree_l2_norm(self._residual)))
        wire = {
            "codec": self._cspec.method,
            "payload": jax.tree.map(np.asarray, payload),
        }
        self._comp_cache = (round_idx, wire)
        return wire

    def _handle_sync(self, msg: Message) -> None:
        t0 = time.monotonic()
        client_idx = int(msg.get(KEY_CLIENT_INDEX))
        round_idx = int(msg.get(KEY_ROUND))
        self.last_round = round_idx
        variables = jax.tree.map(jnp.asarray, msg.get(KEY_MODEL_PARAMS))
        rng = jax.random.fold_in(
            jax.random.fold_in(self.root_key, round_idx), client_idx
        )
        # the np.asarray conversion blocks on the async dispatch, so the
        # span covers the real device work, not just the enqueue
        t_loc = time.monotonic()
        with telemetry.maybe_span(
            "local_update", rank=self.rank, round=round_idx,
            client=client_idx,
        ):
            new_vars, n_k, _ = self._local_update(
                variables,
                self.arrays.idx[client_idx],
                self.arrays.mask[client_idx],
                self.arrays.x,
                self.arrays.y,
                rng,
            )
            if self._adversary is not None:
                new_vars = A.corrupt_client_vars(
                    self._adversary, variables, new_vars, round_idx,
                    self.rank,
                )
                telemetry.METRICS.inc("adversary.corrupted_results")
            if self._cspec.enabled():
                result_payload = {
                    KEY_COMPRESSED: self._compress_result(
                        variables, new_vars, round_idx
                    ),
                }
            else:
                result_payload = {
                    KEY_MODEL_PARAMS: jax.tree.map(np.asarray,
                                                   new_vars),
                }
        t_send = time.monotonic()
        self.send_message(
            Message(
                MSG_TYPE_C2S_RESULT,
                self.rank,
                0,
                {
                    **result_payload,
                    KEY_NUM_SAMPLES: float(n_k),
                    # round tag: lets the server discard a straggler's
                    # result that arrives after its round already closed
                    KEY_ROUND: round_idx,
                },
            )
        )
        m = telemetry.METRICS
        if m.enabled:
            # the client's own round wall (sync received -> result
            # shipped): the fleet-federation whitelist forwards this
            # histogram's bucket deltas on the heartbeat uplink, so
            # rank 0's fleet.perf.round_wall_s answers "p95 client
            # round time across the cohort" from one scrape
            m.observe("perf.round_wall_s", time.monotonic() - t0)
            if ANATOMY.enabled:
                # client-side phase attribution: local compute (incl.
                # compression) as its own fleet-federated histogram —
                # rank 0's fleet.perf.phase.local_s splits the cohort's
                # round wall into compute vs wire from one scrape
                m.observe("perf.phase.local_s", t_send - t_loc)
        if (self.leave_after_round is not None
                and round_idx >= self.leave_after_round):
            # contribute this round's result, THEN depart gracefully:
            # LEAVE after RESULT on the same ordered channel, so the
            # server books the contribution before the departure
            try:
                self.send_message(
                    Message(MSG_TYPE_C2S_LEAVE, self.rank, 0, {})
                )
            except Exception:
                pass  # server gone; heartbeat staleness covers it
            self.left.set()
            telemetry.RECORDER.record("leave", rank=self.rank,
                                      round=round_idx)
            self.finish()

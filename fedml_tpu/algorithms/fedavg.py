"""FedAvg family as one compiled round program.

TPU-native redesign of the reference's standalone simulator
(``fedml_api/standalone/fedavg/fedavg_api.py:40-115``) and the FedOpt /
FedProx / FedNova / robust-aggregation variants — each reference variant is a
configuration of the same compiled round:

- client sampling          (``FedAVGAggregator.client_sampling``)
- vmapped local SGD        (``FedAVGTrainer.train`` x cohort, in parallel)
- weighted pytree mean     (``FedAVGAggregator.aggregate``)
- server optimizer step    (``fedopt/FedOptAggregator`` pseudo-gradient)
- robust preprocessing     (``fedml_core/robustness/robust_aggregation.py``)
- FedNova tau-normalization(``standalone/fednova/fednova.py:97``)

One ``jax.jit`` round; all state device-resident; the python loop only
sequences rounds and reads metrics.

The server aggregation is written once, parameterized by a :class:`Reducer`
— plain in-device reduction for the single-chip simulator, ``psum`` /
``all_gather`` over the ``clients`` mesh axis for the sharded runtime
(:mod:`fedml_tpu.parallel.client_parallel`) — so the two paths cannot drift.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.config import ExperimentConfig, FedConfig, TrainConfig
from fedml_tpu.core import adversary as A
from fedml_tpu.core.anatomy import ANATOMY
from fedml_tpu.core import bulk as BK
from fedml_tpu.core import compress as C
from fedml_tpu.core import elastic as E
from fedml_tpu.core import memscope as M
from fedml_tpu.core import random as R
from fedml_tpu.core import robust, telemetry, tree as T
from fedml_tpu.core import statebank as SB
from fedml_tpu.core import streamdef as SD
from fedml_tpu import peft as PF
from fedml_tpu.peft import personal as PP
from fedml_tpu.data.federated import FederatedArrays, FederatedData, arrays_and_batch
from fedml_tpu.algorithms.base import (
    build_cohort_local_update,
    build_evaluator,
    build_local_update,
    cohort_update_supported,
    finalize_sums,
    make_task,
)
from fedml_tpu.models.base import FedModel

Pytree = Any


def consume_round_counters(train_metrics: dict) -> dict:
    """Pop device-computed counter values out of a round's metric dict
    and feed them to the process metrics registry (the round loops —
    :meth:`FedAvgSim.run` and the harness — call this where they
    already force the metrics to host, so the bench's sync-free
    ``run_round`` loop pays nothing)."""
    rej = train_metrics.pop("nonfinite_rejected", None)
    if rej is not None:
        r = float(rej)
        if r:
            telemetry.METRICS.inc("robust.nonfinite_rejected", r)
            telemetry.RECORDER.record("nonfinite_rejected", count=r,
                                      path="sim")
    res = train_metrics.pop("compress_residual_norm", None)
    if res is not None:
        # the error-feedback carry (docs/OBSERVABILITY.md): bounded ==
        # compression error is telescoping carry, not accumulating bias
        telemetry.METRICS.gauge("compress.residual_norm", float(res))
    # round-boundary device-memory sample (core/memscope.py): every
    # sim round loop funnels through here exactly once per round with
    # the metrics already forced to host — the natural boundary for
    # the live mem.* gauges. One attribute check when telemetry is off.
    M.MONITOR.sample()
    return train_metrics


class ServerState(NamedTuple):
    variables: Pytree  # full model variables (params [+ batch_stats])
    opt_state: Any  # server optimizer state
    momentum: Pytree  # global momentum buffer (FedNova gmf)
    round: jax.Array  # int32


class Reducer(NamedTuple):
    """How to reduce per-client quantities over the (possibly sharded)
    cohort. ``wmean(stacked, w)``: weighted mean over ALL clients;
    ``sum_scalar``: global scalar sum; ``gather``: full stacked tree (for
    coordinate-wise defenses); ``axis``: the mesh axis the cohort is
    sharded over (None on a local reduce) — defense rules with a
    blockwise-shardable term (the Krum gram) key their sharded fast
    path off it."""

    wmean: Callable[[Pytree, jax.Array], Pytree]
    sum_scalar: Callable[[jax.Array], jax.Array]
    gather: Callable[[Pytree], Pytree]
    axis: str | None = None


def local_reducer() -> Reducer:
    return Reducer(
        wmean=T.tree_weighted_mean,
        sum_scalar=lambda s: s,
        gather=lambda t: t,
    )


def psum_reducer(axis: str) -> Reducer:
    def wmean(stacked, w):
        n_total = jax.lax.psum(jnp.sum(w), axis)
        local = T.tree_weighted_sum(stacked, w)
        return jax.tree.map(lambda v: jax.lax.psum(v, axis) / n_total, local)

    return Reducer(
        wmean=wmean,
        sum_scalar=lambda s: jax.lax.psum(s, axis),
        gather=lambda t: jax.tree.map(
            lambda v: jax.lax.all_gather(v, axis, tiled=True), t
        ),
        axis=axis,
    )


def make_server_optimizer(name: str, lr: float, momentum: float):
    """Server optimizers (reference ``fedopt/optrepo.py:7`` reflection over
    torch optimizers; ``sgd`` with lr=1 and no momentum == plain FedAvg)."""
    if name == "sgd":
        return optax.sgd(lr, momentum=momentum if momentum else None)
    if name == "adam":
        return optax.adam(lr)
    if name == "adagrad":
        return optax.adagrad(lr)
    if name == "yogi":
        return optax.yogi(lr)
    raise ValueError(f"unknown server optimizer: {name}")


def server_update(
    fed: FedConfig,
    train: TrainConfig,
    steps_per_epoch: int,
    batch_size: int,
    state: ServerState,
    stacked_vars: Pytree,
    n_k: jax.Array,
    rkey: jax.Array,
    red: Reducer,
    valid: jax.Array | None = None,
) -> ServerState:
    """One server step from stacked client results. Shared between the
    single-device and mesh-sharded rounds (reference equivalents:
    ``FedAVGAggregator.aggregate``, ``FedOptAggregator``,
    ``fednova.py`` tau-normalized averaging, ``RobustAggregator``).

    ``valid`` (``[C]`` bool, possibly traced) marks the live rows of a
    bucket-padded elastic cohort (:mod:`fedml_tpu.core.elastic`):
    padded rows carry the global variables (delta exactly zero) and
    weight 0, and every defense rule masks them out — the aggregate
    depends only on the live rows (content-blind, pinned bitwise in
    ``tests/test_elastic.py``) while the compiled program's shapes —
    and therefore the XLA cache — depend only on the bucket."""
    global_params = state.variables["params"]
    deltas = jax.tree.map(
        lambda s, g: s - g[None], stacked_vars["params"], global_params
    )

    # the full defense stack (core/robust.py): clip each delta, reduce
    # under the configured rule (mean/median/trimmed_mean/krum/
    # multikrum/fltrust), then noise the aggregate. The default
    # pipeline (mean, clip 0, noise 0) is byte-identical to the plain
    # weighted mean.
    pipe = robust.DefensePipeline.from_fed(fed)
    deltas = pipe.preprocess(deltas)

    robust.check_fednova_compat(fed.algorithm, pipe.method)
    if fed.algorithm == "fednova":
        # tau_k = true local steps (real-first batch ordering makes this
        # exact); d_k = delta_k / tau_k; delta = tau_eff * sum p_k d_k.
        # Padded rows are weight-0 everywhere n_k appears, so they
        # vanish from n_total, tau_eff, and the weighted mean exactly.
        tau = (
            jnp.ceil(n_k / batch_size).clip(1, steps_per_epoch)
            * train.epochs
        )
        n_total = red.sum_scalar(jnp.sum(n_k))
        tau_eff = red.sum_scalar(jnp.sum(n_k * tau)) / n_total
        d = jax.tree.map(
            lambda v: v / tau.reshape((-1,) + (1,) * (v.ndim - 1)), deltas
        )
        agg_delta = T.tree_scale(red.wmean(d, n_k), tau_eff)
    else:
        agg_delta = pipe.reduce(deltas, n_k, red, valid)

    agg_delta = pipe.postprocess(agg_delta, jax.random.fold_in(rkey, 1))
    new_params, new_opt_state, new_momentum = _server_delta_step(
        fed, state, agg_delta
    )

    # non-param collections (batch_stats): plain weighted mean, like the
    # reference's full-state_dict averaging (FedAVGAggregator.py:73-81)
    other = {
        k: red.wmean(v, n_k)
        for k, v in stacked_vars.items()
        if k != "params"
    }
    return ServerState(
        variables={**other, "params": new_params},
        opt_state=new_opt_state,
        momentum=new_momentum,
        round=state.round + 1,
    )


def _server_delta_step(fed: FedConfig, state: ServerState,
                       agg_delta: Pytree):
    """The post-reduce server tail — global momentum buffer (FedNova
    gmf) + server optimizer step — shared verbatim by the stacked
    (:func:`server_update`) and streaming
    (:func:`server_update_from_partials`) aggregation paths, so the two
    cannot drift past the reduce itself. Returns ``(new_params,
    new_opt_state, new_momentum)``."""
    global_params = state.variables["params"]
    if fed.gmf > 0:
        new_momentum = T.tree_add(
            T.tree_scale(state.momentum, fed.gmf), agg_delta
        )
        agg_delta = new_momentum
    else:
        new_momentum = state.momentum

    opt = make_server_optimizer(
        fed.server_optimizer, fed.server_lr, fed.server_momentum
    )
    pseudo_grad = T.tree_scale(agg_delta, -1.0)
    updates, new_opt_state = opt.update(
        pseudo_grad, state.opt_state, global_params
    )
    new_params = optax.apply_updates(global_params, updates)
    return new_params, new_opt_state, new_momentum


def fold_block_partials(
    fed: FedConfig,
    train: TrainConfig,
    steps_per_epoch: int,
    batch_size: int,
    state: ServerState,
    stacked_vars: Pytree,
    n_k: jax.Array,
    msums: dict,
    rejected: jax.Array,
) -> BK.RoundPartials:
    """Reduce ONE block of (injected/healed/screened) stacked local
    results to its O(model) :class:`~fedml_tpu.core.bulk.RoundPartials`
    — the streaming half of :func:`server_update`. Mirrors the stacked
    reduce head exactly: delta against the global params, defense
    preprocess (per-row clip), FedNova's per-row tau normalization.
    Weighted sums ride ``T.tree_weighted_sum`` (the same f32
    accumulator ``tree_weighted_mean`` uses), so bulk-vs-stacked parity
    is the reduce-reassociation ulp band and nothing more (pinned in
    ``tests/test_bulk.py``)."""
    pipe = robust.DefensePipeline.from_fed(fed)
    global_params = state.variables["params"]
    deltas = jax.tree.map(
        lambda s, g: s - g[None], stacked_vars["params"], global_params
    )
    deltas = pipe.preprocess(deltas)
    nf = n_k.astype(jnp.float32)
    if fed.algorithm == "fednova":
        tau = (
            jnp.ceil(n_k / batch_size).clip(1, steps_per_epoch)
            * train.epochs
        )
        deltas = jax.tree.map(
            lambda v: v / tau.reshape((-1,) + (1,) * (v.ndim - 1)),
            deltas,
        )
        tau_wsum = jnp.sum(nf * tau)
    else:
        tau_wsum = jnp.zeros((), jnp.float32)

    return BK.RoundPartials(
        delta_wsum=T.tree_weighted_sum(deltas, nf),
        other_wsum={
            k: T.tree_weighted_sum(v, nf)
            for k, v in stacked_vars.items()
            if k != "params"
        },
        n_sum=jnp.sum(nf),
        tau_wsum=tau_wsum,
        msums=jax.tree.map(jnp.sum, msums),
        rejected=rejected,
    )


def server_update_from_partials(
    fed: FedConfig,
    state: ServerState,
    partials: BK.RoundPartials,
    rkey: jax.Array,
    agg_delta: Pytree | None = None,
) -> ServerState:
    """One server step from GLOBALLY-reduced streaming partials — the
    bulk twin of :func:`server_update`, sharing its exact tail
    (:func:`_server_delta_step`). ``partials`` must already be summed
    over every block (and every shard: the mesh runtime psums the
    O(model) partials before calling this, replacing the stacked
    wmean/gather collectives). The ``mean``/FedNova reduce rules fold
    their aggregate out of ``partials`` directly; a streamed defense
    (:mod:`fedml_tpu.core.streamdef`) passes the sketch-decided
    ``agg_delta`` override instead — the non-param collections still
    reduce as weighted means of the partials, exactly what the stacked
    reducer does under any defense rule. The assert is the
    traced-program backstop for a reduce rule that is neither."""
    pipe = robust.DefensePipeline.from_fed(fed)
    assert (pipe.method in BK.BULK_REDUCE_RULES
            or agg_delta is not None), pipe.method
    global_params = state.variables["params"]
    # the same max(Σw, 1e-12) guard tree_weighted_mean applies, so the
    # degenerate all-zero-weight round degrades identically
    denom = jnp.maximum(partials.n_sum, 1e-12)
    if agg_delta is None:
        agg_delta = jax.tree.map(
            lambda s, g: (s / denom).astype(g.dtype),
            partials.delta_wsum, global_params,
        )
        if fed.algorithm == "fednova":
            # tau_eff = Σ n·tau / Σ n, exactly the stacked formula with
            # both sums pre-reduced
            agg_delta = T.tree_scale(
                agg_delta, partials.tau_wsum / partials.n_sum
            )
    else:
        agg_delta = jax.tree.map(
            lambda d, g: d.astype(g.dtype), agg_delta, global_params
        )
    agg_delta = pipe.postprocess(agg_delta, jax.random.fold_in(rkey, 1))
    new_params, new_opt_state, new_momentum = _server_delta_step(
        fed, state, agg_delta
    )
    other = {
        k: jax.tree.map(
            lambda s, g: (s / denom).astype(g.dtype),
            v, state.variables[k],
        )
        for k, v in partials.other_wsum.items()
    }
    return ServerState(
        variables={**other, "params": new_params},
        opt_state=new_opt_state,
        momentum=new_momentum,
        round=state.round + 1,
    )


# canonical implementations live in stack_utils (shared with the GAN
# family's vmapped path); re-exported here for the established import
# path
from fedml_tpu.algorithms.stack_utils import (  # noqa: E402
    resolve_cohort_groups as _resolve_cohort_groups,
    size_grouped_lanes as _size_grouped_lanes,
)


def _grouped_cohort_call(
    cohort_update, groups: int, variables, idx_rows, mask_rows, x, y, ckeys
):
    """Run the fused cohort update in ``groups`` size-sorted sub-groups.

    Clients are sorted by sample count (descending) so each sub-group's
    dynamic trip count is set by ITS largest member, not the cohort's;
    results are unsorted back so callers see cohort order. Each client's
    trajectory depends only on (globals, its rows, its key) — sorting and
    grouping change scheduling, not numerics (same equality class as the
    fused-vs-vmapped comparison, tests/test_cohort_conv.py). ``groups``
    was resolved at build time against the SAME cohort size the fused
    update was compiled for, so the helper's re-resolution is a no-op
    here (a lane-count mismatch would fail loudly on the update's
    static shapes regardless)."""
    if groups == 1:
        return cohort_update(variables, idx_rows, mask_rows, x, y, ckeys)
    return _size_grouped_lanes(
        lambda i, m, k: cohort_update(variables, i, m, x, y, k),
        (idx_rows, mask_rows, ckeys), mask_rows, groups,
    )


class FedAvgSim:
    """Compiled federated simulation on one chip (see
    :mod:`fedml_tpu.parallel` for the mesh-sharded version)."""

    def __init__(
        self,
        model: FedModel,
        data: FederatedData,
        cfg: ExperimentConfig,
        sampler=None,
    ):
        # cohort sampler: (key, num_clients, clients_per_round) -> ids.
        # Default = global uniform without replacement; the sharded runtime's
        # equality tests pass R.sample_clients_stratified to mirror its
        # per-shard sampling on one device.
        self.sampler = sampler or R.sample_clients
        self.cfg = cfg
        # surfaced at construction instead of the first traced round
        robust.check_fednova_compat(cfg.fed.algorithm,
                                    cfg.fed.robust_method)
        # -- parameter-efficient fine-tuning (fedml_tpu.peft, docs/
        # PERFORMANCE.md "Parameter-efficient federated fine-tuning"):
        # with cfg.fed.peft='lora' the model's targeted projections are
        # wrapped with zero-init low-rank branches and the rounds below
        # train/aggregate ONLY the adapter + head subtree — the frozen
        # base never grows an optimizer state, a delta, or a wire
        # payload. Off by default: build_peft returns the model
        # untouched and every path stays byte-identical.
        model, self._peft = PF.build_peft(model, cfg)
        self.model = model
        # personalization bank: a client-id-keyed ClientStateBank
        # (core/statebank.py), created lazily on the first round;
        # `_adapter_bank` exposes its raw rows for callers
        self._bank_adapter = None
        self.task = make_task(data.task)
        self._prepare_data(data, cfg)
        # token-model sanity: an embed table smaller than the data's
        # id space makes XLA CLAMP every out-of-range lookup — the
        # run trains and reports metrics on silently corrupted
        # gathers. Surface it here, where both sides are known.
        vocab = getattr(self.model.module, "vocab_size", None)
        if (self.task.name == "nwp" and vocab is not None
                and vocab < self.arrays.num_classes):
            raise ValueError(
                f"model vocab_size {vocab} < the dataset's token-id "
                f"space {self.arrays.num_classes}: out-of-range "
                "embedding lookups clamp silently. Set --num_classes "
                "(or model extra vocab_size) to the dataset's vocab "
                f"({self.arrays.num_classes})."
            )
        max_n = self.arrays.max_client_samples
        self.steps_per_epoch = max_n // self.batch_size
        self.local_update = build_local_update(
            model, self.task, cfg.train, self.batch_size, max_n,
            partition=self._peft.part if self._peft else None,
        )
        # cohort-grouped fast path: run the whole cohort as ONE widened
        # network instead of vmapping per-client nets (same numerics,
        # ~3x on conv models — see fedml_tpu.models.cohort). Explicitly
        # disabled with TrainConfig(cohort_fused=False).
        cohort = min(cfg.fed.clients_per_round, cfg.data.num_clients)
        # -- elastic shape bucketing (core/elastic.py, docs/
        # FAULT_TOLERANCE.md "Elastic membership"): the round program is
        # compiled for the power-of-two BUCKET above the cohort, with
        # the live count a traced operand — set_cohort_size() then
        # changes the cohort within the bucket without a recompile.
        # Padded slots run masked local updates (weight 0, params
        # healed to the global model) that provably cannot perturb any
        # aggregation rule. Off by default: the static path stays
        # byte-identical to its pre-elastic self.
        self._elastic = bool(cfg.fed.elastic_buckets)
        if self._elastic and sampler is not None:
            # the bucketed round draws a full-bucket permutation whose
            # live PREFIX is the cohort (_sample_bucket) — a
            # (key, n, k) sampler cannot express that contract, and
            # silently ignoring it would report uniform-sampling
            # results under the user's sampler's name
            raise ValueError(
                "elastic_buckets=True is incompatible with a custom "
                "cohort sampler: the compiled bucketed round draws its "
                "own full-bucket permutation (core/elastic.py). "
                "Disable elastic buckets or drop the sampler."
            )
        self._bucket = (
            min(E.bucket_for(cohort), cfg.data.num_clients)
            if self._elastic else cohort
        )
        self._n_active = cohort
        # -- bulk-client streaming (core/bulk.py, docs/PERFORMANCE.md
        # "Bulk-client execution"): with cfg.fed.client_block_size = B
        # the round streams the cohort through the device in blocks of
        # B vmapped local updates, each folded into an O(model)
        # partial-sum scan carry — peak memory O(B + model), not O(C).
        # Selection defenses stream as two-pass sketches
        # (core/streamdef.py); compression and personalization keep
        # their per-client state in client-id-keyed ClientStateBanks
        # (core/statebank.py) riding the scan carry. Off by default:
        # the stacked round stays byte-identical.
        self._bulk = BK.BulkSpec.from_fed(cfg.fed)
        self._stream_defense = (
            cfg.fed.robust_method
            if (self._bulk.enabled()
                and cfg.fed.robust_method in SD.STREAM_METHODS)
            else None
        )
        if self._bulk.enabled():
            BK.check_bulk_compat(cfg.fed, cfg.adversary)
            self._block_size = self._bulk.block_size
            # elastic buckets apply to the BLOCK COUNT: the compiled
            # scan length is the power-of-two bucket of ceil(C/B)
            # blocks, so cohort churn within it is a cache hit
            self._n_blocks = BK.plan_blocks(
                cohort, self._block_size, self._elastic
            )
            self._slots = self._n_blocks * self._block_size
            # the live cohort can grow into the headroom blocks, but
            # never past the population (sampling is w/o replacement)
            self._max_live = min(self._slots, cfg.data.num_clients)
        self._cohort_groups = _resolve_cohort_groups(
            cfg.train.cohort_groups, cohort
        )
        self._cohort_update = (
            build_cohort_local_update(
                model, self.task, cfg.train, self.batch_size, max_n,
                cohort // self._cohort_groups,
            )
            if cfg.train.cohort_fused
            and cohort_update_supported(model, cfg.train)
            # the cohort-grouped network bakes the cohort size into its
            # widened layer shapes — bucketing covers the vmapped path
            and not self._elastic
            # the bulk engine streams the VMAPPED update per block (the
            # widened cohort network would bake C back into one program)
            and not self._bulk.enabled()
            # the partitioned local update is the vmapped builder's
            # (no cohort-eligible architecture is LoRA-injectable
            # today; stated rather than assumed)
            and self._peft is None
            else None
        )
        self.evaluator = build_evaluator(model, self.task)
        self.root_key = jax.random.key(cfg.seed)
        # -- wire compression (core/compress.py, docs/PERFORMANCE.md
        # "Wire compression"): with cfg.fed.compress the round applies
        # the exact compress->decompress arithmetic the deploy wire
        # sees — per-slot, inside the compiled round, with the
        # error-feedback residual carried across rounds as a donated
        # [bucket, ...] operand. Off by default: the dense round is
        # byte-identical (no extra operand, no residual allocation).
        self._cspec = C.CompressionSpec.from_fed(cfg.fed, seed=cfg.seed)
        self._ef_residual = None  # lazy zero carry, [bucket, ...]
        # bulk mode keeps the EF carry in a client-id-keyed
        # ClientStateBank instead of the slot-keyed [bucket, ...] carry
        # (the residual follows the CLIENT across rounds; core/
        # statebank.py) — also created lazily, checkpointed alongside
        # the adapter bank (bank_state/restore_banks)
        self._ef_bank = None
        if self._peft is not None and self._peft.personalized:
            # the private adapter bank rides as a donated operand
            # (arg 4 of _round) exactly like the EF residual would —
            # compress+personalize is rejected, so the two never
            # coexist
            donate = (0, 4)
        elif self._cspec.enabled():
            donate = (0, 3)
        else:
            donate = (0,)
        # the round program is an instrumented AOT site
        # (core/memscope.py): compiles are explicit .lower().compile()
        # calls — byte-identical lowering to a first jit call — so
        # every compile is timed (mem.compile_s.sim_round), its
        # memory_analysis recorded (mem.program.*), and the donated
        # state/residual audited is_deleted after the first execution.
        # ProgramSite exposes _cache_size, so the elastic paths'
        # mirror_jit_cache accounting is unchanged. Bulk rounds get
        # their own program family (sim_bulk.<blocks>.<B>) so the
        # mem.program.* accounting and the donation audit name the
        # block program distinctly from the stacked one.
        family = "sim_bulk" if self._bulk.enabled() else "sim_round"
        self._round_fn = M.ProgramSite(self._round, family=family,
                                       donate_argnums=donate)
        # -- fused multi-round execution (core/fuse.py, docs/
        # PERFORMANCE.md "Round fusion"): with fuse_rounds K > 1 ONE
        # compiled program runs K complete rounds as a lax.scan over
        # the round body — ServerState (and the error-feedback
        # residual) ride as donated scan carries, per-round train
        # metrics stack into [K, ...] outputs the driver consumes once
        # per block. Cohort sampling folds in the CARRIED round
        # counter, so the sampled cohorts are bitwise-identical to the
        # unfused loop's. K = 1 (the default) never builds the block
        # program: the per-round path stays byte-identical.
        fuse = cfg.fed.fuse_rounds
        self._fuse = 1 if fuse is None else int(fuse)
        if self._fuse < 1:
            raise ValueError(
                f"fuse_rounds must be >= 1, got {cfg.fed.fuse_rounds}"
            )
        # the sharded runtime rebinds this to its shard_map'd round so
        # the SAME fused-block scan wraps either body
        self._round_impl = self._round
        self._block_fn = (
            M.ProgramSite(
                self._fused_block,
                family=(
                    "sim_bulk_block" if self._bulk.enabled()
                    else "sim_block"
                ),
                static_argnums=(5,), donate_argnums=donate,
            )
            if self._fuse > 1 else None
        )
        # process-global headroom threshold for the memory monitor
        # (--mem_headroom_warn; docs/OBSERVABILITY.md "Memory &
        # compilation")
        M.MONITOR.headroom_warn = float(
            getattr(cfg.fed, "mem_headroom_warn", 0.9) or 0.9
        )

    def _prepare_data(self, data: FederatedData, cfg: ExperimentConfig):
        """Resolve device data + batch size. The mesh-sharded subclass
        overrides this to keep the global arrays host-side (its training
        data lives in per-shard banks instead)."""
        self.arrays, self.batch_size = arrays_and_batch(data, cfg.data)

    # -- initialization ----------------------------------------------------
    def init(self) -> ServerState:
        variables = self.model.init(
            jax.random.fold_in(self.root_key, 0x7FFFFFFF)
        )
        opt = make_server_optimizer(
            self.cfg.fed.server_optimizer,
            self.cfg.fed.server_lr,
            self.cfg.fed.server_momentum,
        )
        # PEFT: server optimizer state + momentum live at the
        # AGGREGATED subtree's shape only (adapters + head, or the
        # shared head under personalization) — the frozen base never
        # grows server-side state
        opt_params = (
            variables["params"] if self._peft is None
            else self._peft.agg_part.trainable(variables["params"])
        )
        if self._peft is not None:
            self._note_peft(variables)
        return ServerState(
            variables=variables,
            opt_state=opt.init(opt_params),
            momentum=T.tree_zeros_like(opt_params),
            round=jnp.asarray(0, jnp.int32),
        )

    def _note_peft(self, variables) -> None:
        """Host-side PEFT accounting at init (docs/OBSERVABILITY.md
        ``peft.*`` vocabulary) — one attribute check when telemetry
        is off."""
        m = telemetry.METRICS
        if not m.enabled:
            return
        params = variables["params"]
        trainable, frozen = self._peft.counts(params)
        m.gauge("peft.trainable_params", float(trainable))
        m.gauge("peft.frozen_params", float(frozen))
        m.gauge(
            "peft.adapter_wire_mb",
            self._peft.adapter_wire_bytes(params) / 1e6,
        )
        m.gauge(
            "peft.wire_ratio",
            PF.compound_wire_ratio(self._peft, self._cspec, params),
        )

    # -- elastic cohort control (core/elastic.py) --------------------------
    def set_cohort_size(self, n: int) -> None:
        """Change the live cohort size for subsequent rounds WITHOUT a
        recompile, as long as ``n`` fits the compiled bucket — the
        simulator face of elastic membership (a churn schedule walks
        this up and down; docs/FAULT_TOLERANCE.md "Elastic
        membership")."""
        if not self._elastic:
            raise ValueError(
                "set_cohort_size requires FedConfig(elastic_buckets="
                "True) — the static round program bakes the cohort "
                "size into its shapes"
            )
        if self._bulk.enabled():
            # bulk mode buckets the BLOCK COUNT: any cohort within the
            # compiled block grid reuses the one scan program
            if not (1 <= n <= self._max_live):
                raise ValueError(
                    f"cohort size {n} does not fit the compiled "
                    f"{self._n_blocks}x{self._block_size} block grid "
                    f"(live cohort must stay in [1, {self._max_live}]; "
                    "grow needs a new simulator)"
                )
            self._n_active = n
            return
        if not (1 <= n <= self._bucket):
            raise ValueError(
                f"cohort size {n} does not fit the compiled bucket "
                f"{self._bucket} (grow needs a new simulator; within "
                f"[1, {self._bucket}] changes are free)"
            )
        self._n_active = n

    def _sample_bucket(self, key, num_clients: int) -> jax.Array:
        """Sample BUCKET client ids; the live prefix of the draw is the
        round's cohort (the active mask hides the rest)."""
        if self._bucket >= num_clients:
            # a permutation, not arange: the active mask keeps the live
            # PREFIX of this draw, so a fixed order would pin the same
            # first-n_active clients into every round once the bucket
            # covers the whole population
            return jax.random.permutation(key, num_clients).astype(
                jnp.int32
            )
        return jax.random.choice(
            key, num_clients, shape=(self._bucket,), replace=False
        ).astype(jnp.int32)

    def _sample_slot_ids(self, key, num_clients: int) -> jax.Array:
        """Elastic-bulk sampling: ``[slots]`` client ids whose live
        PREFIX is the round's cohort (the bulk twin of
        :meth:`_sample_bucket` — a permutation when the grid covers the
        population, so the live prefix never pins the same clients).
        Slots beyond the population are dead by construction
        (``_max_live``) and carry the out-of-range SENTINEL id
        (``num_clients``) so they can never alias a real client's bank
        row (core/statebank.py sentinel padding)."""
        draw = min(self._slots, num_clients)
        if draw >= num_clients:
            ids = jax.random.permutation(key, num_clients).astype(
                jnp.int32
            )
        else:
            ids = jax.random.choice(
                key, num_clients, shape=(draw,), replace=False
            ).astype(jnp.int32)
        return SB.pad_ids(ids, self._slots, num_clients)

    # -- one round ---------------------------------------------------------
    def _locals(self, state: ServerState, arrays: FederatedArrays,
                n_active=None):
        """Sampling + local updates, the pre-aggregation prefix of the
        round: returns (stacked_vars, n_k, metric sums, round key,
        cohort). Shared with aggregation rules that live outside the
        compiled round (e.g. TurboAggregate secure aggregation,
        :class:`fedml_tpu.algorithms.mpc.SecureFedAvgSim`) so alternate
        servers cannot drift from the canonical sampling/local math.
        The sampled cohort rides the return value so consumers (the
        adversary injection gate) never re-derive the draw."""
        cfg = self.cfg.fed
        rkey = R.round_key(self.root_key, state.round)
        if n_active is not None:
            cohort = self._sample_bucket(
                jax.random.fold_in(rkey, 0), arrays.num_clients
            )
        else:
            cohort = self.sampler(
                jax.random.fold_in(rkey, 0),
                arrays.num_clients,
                cfg.clients_per_round,
            )
        ckeys = jax.vmap(lambda c: R.client_key(rkey, c))(cohort)
        idx_rows = arrays.idx[cohort]
        mask_rows = arrays.mask[cohort]

        if self._cohort_update is not None:
            stacked_vars, n_k, msums = _grouped_cohort_call(
                self._cohort_update,
                self._cohort_groups,
                state.variables,
                idx_rows,
                mask_rows,
                arrays.x,
                arrays.y,
                ckeys,
            )
        else:
            stacked_vars, n_k, msums = jax.vmap(
                self.local_update, in_axes=(None, 0, 0, None, None, 0)
            )(state.variables, idx_rows, mask_rows, arrays.x, arrays.y, ckeys)
        return stacked_vars, n_k, msums, rkey, cohort

    def _inject_adversaries(self, state, arrays, stacked_vars, cohort):
        """Seeded Byzantine injection (core/adversary.py): adversarial
        cohort slots get their params replaced by ``global + attacked
        delta``; honest slots keep their EXACT local-update output (the
        select happens at the variables level, so no honest value is
        rewritten through a subtract/add round trip). ``cohort`` is the
        draw `_locals` actually used — never re-derived."""
        adv = self.cfg.adversary
        mask = A.cohort_mask(adv, cohort, arrays.num_clients)
        gp = state.variables["params"]
        deltas = jax.tree.map(
            lambda s, g: s - g[None], stacked_vars["params"], gp
        )
        # cohort keys the gauss draw per (round, client id) — chunking-
        # independent, so the bulk engine's per-block injection is
        # bitwise-equal to the stacked round at matched seeds
        attacked = A.corrupt_stacked_deltas(
            adv, deltas, state.round, cohort
        )
        params = jax.tree.map(
            lambda s, g, a: jnp.where(
                mask.reshape((-1,) + (1,) * (s.ndim - 1)),
                (g[None] + a).astype(s.dtype),
                s,
            ),
            stacked_vars["params"], gp, attacked,
        )
        return {**stacked_vars, "params": params}

    def _screen_nonfinite(self, state, stacked_vars, n_k):
        """NaN/Inf screening on the simulator path — the same contract
        as the deploy-path message handler (``_result_is_finite``): a
        poisoned result must never enter the aggregate. Static shapes
        cannot drop a row, so a screened client is replaced by the
        global model (delta exactly 0 — a neutral no-op vote for the
        coordinate defenses) with zero aggregation weight. All-finite
        cohorts pass through byte-identically (``where(True, x, _) is
        x`` value-wise)."""
        ok = robust.finite_client_mask(stacked_vars, n_k)

        def heal(s, g):
            m = ok.reshape((-1,) + (1,) * (s.ndim - 1))
            return jnp.where(m, s, g[None].astype(s.dtype))

        cleaned = jax.tree.map(heal, stacked_vars, state.variables)
        n_k = jnp.where(ok, n_k, jnp.zeros_like(n_k))
        rejected = (ok.shape[0] - jnp.sum(ok)).astype(jnp.float32)
        return cleaned, n_k, rejected

    def _wire_roundtrip(self, state, stacked_vars, residual, rkey,
                        live):
        """The in-round wire model (core/compress.py): delta each
        slot's variables against the global model, fold in the
        error-feedback carry, compress->decompress with the SAME
        arithmetic the deploy wire applies, and rebuild the variables
        from the decompressed delta. Padded slots of an elastic bucket
        get their carry zeroed (a slot that just left the live prefix
        must not smuggle its stale residual into a healed row's
        content)."""
        gp = state.variables
        deltas = jax.tree.map(
            lambda s, g: s - g[None], stacked_vars, gp
        )
        deq, new_residual = C.roundtrip_stacked(
            self._cspec, deltas, residual, rkey
        )
        stacked_vars = jax.tree.map(
            lambda g, d: (g[None] + d).astype(d.dtype), gp, deq
        )
        if live is not None:
            new_residual = jax.tree.map(
                lambda r: jnp.where(
                    live.reshape((-1,) + (1,) * (r.ndim - 1)),
                    r, jnp.zeros((), r.dtype),
                ),
                new_residual,
            )
        return stacked_vars, new_residual

    def _bulk_round(self, state: ServerState, arrays: FederatedArrays,
                    n_active=None, ef_bank=None, adapter_bank=None):
        """The block-streamed round body (core/bulk.py,
        docs/PERFORMANCE.md "Bulk-client execution"): sample the
        cohort, chunk it into ``block_size`` slots, run each block
        through the SAME vmapped local update / adversary injection /
        wire roundtrip / padding-heal / non-finite screen the stacked
        round applies, and fold each block's
        :func:`fold_block_partials` into the O(model) scan carry. Peak
        memory is O(block + model + sketch) — no ``[C, ...]`` stacked
        operand ever materializes. The final server step is
        :func:`server_update_from_partials`, which shares
        :func:`server_update`'s exact post-reduce tail.

        ``ef_bank`` (the compression error-feedback
        :class:`~fedml_tpu.core.statebank.ClientStateBank`) and
        ``adapter_bank`` (the PEFT personalization bank) ride the scan
        carry and come back updated; compress+personalize stays
        rejected, so at most one is non-None. A streamed defense
        (:mod:`fedml_tpu.core.streamdef`) turns the body into TWO
        passes over the same blocks: pass 1 folds partials + the
        defense sketch (EF rows read-only), the selection/quantile
        decision is made from the sketch, pass 2 folds the decided
        aggregate (and performs the authoritative EF write — both
        passes recompute the identical deterministic local updates, so
        the roundtrip inputs match bitwise)."""
        cfg = self.cfg.fed
        rkey = R.round_key(self.root_key, state.round)
        skey = jax.random.fold_in(rkey, 0)
        # PEFT view: partials, healing, and the server step fold only
        # the aggregated subtree (local updates keep the FULL state —
        # the frozen base is needed for the forward pass)
        view = (
            state if self._peft is None
            else self._peft.view_state(state)
        )
        if n_active is not None:
            # elastic: full-grid draw, live prefix = the traced cohort
            ids = self._sample_slot_ids(skey, arrays.num_clients)
            live = E.active_mask(self._slots, n_active)
        else:
            # static: the SAME draw the stacked round makes (parity),
            # tail slots padded with the out-of-range sentinel id (a
            # pad slot must never alias a real client's bank row)
            cohort = self.sampler(
                skey, arrays.num_clients, cfg.clients_per_round
            )
            pad = self._slots - cohort.shape[0]
            ids = SB.pad_ids(cohort, self._slots, arrays.num_clients)
            live = (
                E.active_mask(self._slots, cohort.shape[0])
                if pad else None
            )
        if adapter_bank is not None:
            return self._bulk_personal(
                state, view, arrays, ids, live, rkey, adapter_bank
            )

        def local_block(block_ids, block_live, bank, write_bank=True):
            """The stacked round's pre-aggregation prefix, one block at
            a time: vmapped local updates, adversary injection, wire
            roundtrip against the gathered EF rows, pad heal,
            non-finite screen. Returns ``(stacked_vars, n_k, msums,
            rejected, new_bank)`` — ``new_bank`` None unless ``bank``
            rode in and ``write_bank`` held."""
            ckeys = jax.vmap(lambda c: R.client_key(rkey, c))(block_ids)
            idx_rows = arrays.idx[block_ids]
            mask_rows = arrays.mask[block_ids]
            stacked_vars, n_k, msums = jax.vmap(
                self.local_update, in_axes=(None, 0, 0, None, None, 0)
            )(state.variables, idx_rows, mask_rows, arrays.x, arrays.y,
              ckeys)
            if self.cfg.adversary.enabled():
                stacked_vars = self._inject_adversaries(
                    view, arrays, stacked_vars, block_ids
                )
            rows = new_rows = None
            if bank is not None:
                # the in-round wire model against the CLIENT-keyed EF
                # carry (compress.roundtrip_rows): gather this block's
                # rows, roundtrip, scatter back below once the screen
                # has decided which rows survive
                gp = view.variables
                rows = bank.gather(block_ids)
                deltas = jax.tree.map(
                    lambda s, g: s - g[None], stacked_vars, gp
                )
                deq, new_rows = C.roundtrip_rows(
                    self._cspec, deltas, rows, rkey, block_ids
                )
                stacked_vars = jax.tree.map(
                    lambda g, d: (g[None] + d).astype(d.dtype), gp, deq
                )
            if block_live is not None:
                # padded slots (partial final block / elastic headroom)
                # healed exactly like a bucketed stacked round's
                stacked_vars, n_k, msums = E.mask_padded(
                    stacked_vars, n_k, msums, view.variables,
                    block_live,
                )
            ok = robust.finite_client_mask(stacked_vars, n_k)
            stacked_vars, n_k, rejected = self._screen_nonfinite(
                view, stacked_vars, n_k
            )
            new_bank = None
            if bank is not None and write_bank:
                # a poisoned (or non-live) slot keeps its pre-round EF
                # row — the carry follows the CLIENT, not the slot;
                # sentinel pad ids are dropped by the scatter
                keep = ok if block_live is None else ok & block_live
                new_bank = bank.put(
                    block_ids, new_rows, keep=keep, gathered=rows
                )
            return stacked_vars, n_k, msums, rejected, new_bank

        def partials_of(sv, n_k, msums, rejected):
            return fold_block_partials(
                cfg, self.cfg.train, self.steps_per_epoch,
                self.batch_size, view, sv, n_k, msums, rejected,
            )

        if self._stream_defense is None:
            if ef_bank is None:
                def fold_block(block_ids, block_live):
                    sv, n_k, msums, rej, _ = local_block(
                        block_ids, block_live, None
                    )
                    return partials_of(sv, n_k, msums, rej)

                partials = BK.stream_blocks(
                    fold_block, ids, live, self._block_size
                )
                new_ef = None
            else:
                def fold_block(block_ids, block_live, bank):
                    sv, n_k, msums, rej, bank = local_block(
                        block_ids, block_live, bank
                    )
                    return partials_of(sv, n_k, msums, rej), bank

                partials, new_ef = BK.stream_blocks(
                    fold_block, ids, live, self._block_size,
                    banks=ef_bank,
                )
            agg_delta = None
        else:
            partials, agg_delta, new_ef = self._defended_fold(
                view, ids, live, rkey, ef_bank, local_block,
                partials_of,
            )

        new_state = server_update_from_partials(
            cfg, view, partials, rkey, agg_delta=agg_delta
        )
        if self._peft is not None:
            new_state = self._peft.merge_state(new_state, state)
        fin = finalize_sums(partials.msums)
        train_metrics = {
            "train_loss": fin["loss"],
            "train_acc": fin["acc"],
            "nonfinite_rejected": partials.rejected,
        }
        if new_ef is not None:
            return new_state, train_metrics, new_ef
        return new_state, train_metrics

    def _defended_fold(self, view, ids, live, rkey, ef_bank,
                       local_block, partials_of):
        """The two-pass streamed-defense body (core/streamdef.py):
        pass 1 folds ``(RoundPartials, sketch)`` with the EF rows read
        from the UNCHANGED operand bank (no write — the authoritative
        roundtrip happens in pass 2, recomputing identical inputs), the
        defense decision is made from the sketch in-program, pass 2
        folds the decided aggregate (per-coordinate histogram for the
        quantile rules; selection-weighted delta sum for the projection
        rules) and writes the EF bank. Returns ``(partials, agg_delta,
        new_ef_bank)``."""
        cfg = self.cfg.fed
        pipe = robust.DefensePipeline.from_fed(cfg)
        method = pipe.method
        quantile = method in SD.QUANTILE_METHODS
        gp = view.variables["params"]

        def block_deltas(sv):
            # the defenses see the same per-row preprocessed (clipped)
            # deltas the stacked reducer sees
            return pipe.preprocess(jax.tree.map(
                lambda s, g: s - g[None], sv["params"], gp
            ))

        def live_votes(block_live, n_k):
            # quantile rules vote over LIVE rows — a screened client
            # votes its healed zero delta, matching the stacked
            # reducer's valid=live membership
            if block_live is None:
                return jnp.ones(n_k.shape, jnp.float32)
            return block_live.astype(jnp.float32)

        def fold_pass1(block_ids, block_live, block_pos):
            sv, n_k, msums, rej, _ = local_block(
                block_ids, block_live, ef_bank, write_bank=False
            )
            p = partials_of(sv, n_k, msums, rej)
            deltas = block_deltas(sv)
            lv = live_votes(block_live, n_k)
            if quantile:
                sk = SD.fold_moments(SD.flatten_rows(deltas), lv)
            else:
                sk = SD.fold_proj(
                    deltas, n_k.astype(jnp.float32), lv, block_pos,
                    self._slots, rkey,
                )
            return p, sk

        partials, sketch = BK.stream_blocks(
            fold_pass1, ids, live, self._block_size, positions=True
        )

        if quantile:
            lo, width = SD.hist_edges(sketch)

            def block_hist(sv, n_k, block_live):
                return SD.fold_hist(
                    SD.flatten_rows(block_deltas(sv)),
                    live_votes(block_live, n_k), lo, width,
                )

            if ef_bank is None:
                def fold_pass2(block_ids, block_live, block_pos):
                    sv, n_k, *_unused = local_block(
                        block_ids, block_live, None
                    )
                    return block_hist(sv, n_k, block_live)

                hist = BK.stream_blocks(
                    fold_pass2, ids, live, self._block_size,
                    positions=True,
                )
                new_ef = None
            else:
                def fold_pass2(block_ids, block_live, block_pos, bank):
                    sv, n_k, _m, _r, bank = local_block(
                        block_ids, block_live, bank
                    )
                    return block_hist(sv, n_k, block_live), bank

                hist, new_ef = BK.stream_blocks(
                    fold_pass2, ids, live, self._block_size,
                    banks=ef_bank, positions=True,
                )
            if method == "median":
                est = SD.median_from_hist(
                    hist, lo, width, sketch.count
                )
            else:
                est = SD.trimmed_mean_from_hist(
                    hist, lo, width, sketch.count,
                    SD.trim_table(pipe.trim_frac, self._slots),
                )
            return partials, T.tree_unvectorize(est, gp), new_ef

        w, den = SD.selection_weights(
            method, sketch, pipe.num_adversaries, pipe.multikrum_m
        )

        def block_wsum(sv, block_pos):
            return T.tree_weighted_sum(block_deltas(sv), w[block_pos])

        if ef_bank is None:
            def fold_pass2(block_ids, block_live, block_pos):
                sv, *_unused = local_block(block_ids, block_live, None)
                return block_wsum(sv, block_pos)

            wsum = BK.stream_blocks(
                fold_pass2, ids, live, self._block_size, positions=True
            )
            new_ef = None
        else:
            def fold_pass2(block_ids, block_live, block_pos, bank):
                sv, _n, _m, _r, bank = local_block(
                    block_ids, block_live, bank
                )
                return block_wsum(sv, block_pos), bank

            wsum, new_ef = BK.stream_blocks(
                fold_pass2, ids, live, self._block_size,
                banks=ef_bank, positions=True,
            )
        return partials, T.tree_scale(wsum, 1.0 / den), new_ef

    def _bulk_personal(self, state, view, arrays, ids, live, rkey,
                       bank):
        """Personalized PEFT at bulk scale (fedml_tpu.peft.personal ×
        core/bulk.py): each block gathers its clients' private adapter
        rows from the :class:`~fedml_tpu.core.statebank.
        ClientStateBank`, trains with them merged into the shared
        model, folds the SHARED half into :class:`~fedml_tpu.core.bulk.
        RoundPartials`, and scatters the trained rows back through the
        scan carry. The no-leak contract is structural exactly as in
        :meth:`_personal_round` — the aggregate simply does not contain
        the private paths — and the non-finite screen covers BOTH
        halves: a poisoned client contributes nothing to the shared
        aggregate AND keeps its pre-round bank row."""
        cfg = self.cfg.fed
        plan = self._peft
        base_frozen = plan.private.frozen(state.variables["params"])

        def fold_block(block_ids, block_live, bk):
            priv = bk.gather(block_ids)
            ckeys = jax.vmap(lambda c: R.client_key(rkey, c))(block_ids)

            def one(priv_row, idx_row, mask_row, key):
                params_c = plan.private.merge(priv_row, base_frozen)
                vars_c = {**state.variables, "params": params_c}
                out_vars, n_k, msums = self.local_update(
                    vars_c, idx_row, mask_row, arrays.x, arrays.y, key
                )
                trained = out_vars["params"]
                shared = {
                    **{k: v for k, v in out_vars.items()
                       if k != "params"},
                    "params": plan.private.frozen(trained),
                }
                return (shared, plan.private.trainable(trained), n_k,
                        msums)

            shared, new_priv, n_k, msums = jax.vmap(one)(
                priv, arrays.idx[block_ids], arrays.mask[block_ids],
                ckeys,
            )
            if block_live is not None:
                shared, n_k, msums = E.mask_padded(
                    shared, n_k, msums, view.variables, block_live
                )
            # the screen covers BOTH halves; a non-live slot is already
            # healed and zero-weight, so only live non-finite rows
            # count as rejections (and only live finite rows write
            # their bank row)
            ok = robust.finite_client_mask(
                {"shared": shared, "private": new_priv}, n_k
            )
            lv = (
                jnp.ones(ok.shape, bool) if block_live is None
                else block_live
            )
            ok = ok | ~lv

            def heal(s, g):
                m = ok.reshape((-1,) + (1,) * (s.ndim - 1))
                return jnp.where(m, s, g[None].astype(s.dtype))

            shared = jax.tree.map(heal, shared, view.variables)
            n_k = jnp.where(ok, n_k, jnp.zeros_like(n_k))
            rejected = (ok.shape[0] - jnp.sum(ok)).astype(jnp.float32)
            bk = bk.put(block_ids, new_priv, keep=ok & lv,
                        gathered=priv)
            p = fold_block_partials(
                cfg, self.cfg.train, self.steps_per_epoch,
                self.batch_size, view, shared, n_k, msums, rejected,
            )
            return p, bk

        partials, bank = BK.stream_blocks(
            fold_block, ids, live, self._block_size, banks=bank
        )
        new_view = server_update_from_partials(
            cfg, view, partials, rkey
        )
        new_state = plan.merge_state(new_view, state)
        fin = finalize_sums(partials.msums)
        train_metrics = {
            "train_loss": fin["loss"],
            "train_acc": fin["acc"],
            "nonfinite_rejected": partials.rejected,
        }
        return new_state, train_metrics, bank

    def _personal_round(self, state: ServerState,
                        arrays: FederatedArrays, bank, n_active=None):
        """Personalized PEFT round (fedml_tpu.peft.personal,
        docs/PERFORMANCE.md "Parameter-efficient federated
        fine-tuning"): each sampled client trains with ITS OWN private
        adapter row merged into the shared model; only the shared
        (head) subtree is aggregated, and the trained adapter rows are
        scattered back into the bank. The no-leak contract is
        structural: the aggregated view simply does not contain the
        private paths, and the bank scatter writes each row from its
        own client's update only. ``bank`` is the adapter
        :class:`~fedml_tpu.core.statebank.ClientStateBank`; with
        ``n_active`` (elastic buckets) the draw is the full-bucket
        permutation and non-live slots are healed to zero weight AND
        keep their pre-round bank rows. Returns ``(state, metrics,
        bank)``."""
        cfg = self.cfg.fed
        plan = self._peft
        rkey = R.round_key(self.root_key, state.round)
        if n_active is not None:
            cohort = self._sample_bucket(
                jax.random.fold_in(rkey, 0), arrays.num_clients
            )
            live = E.active_mask(self._bucket, n_active)
        else:
            cohort = self.sampler(
                jax.random.fold_in(rkey, 0),
                arrays.num_clients,
                cfg.clients_per_round,
            )
            live = None
        ckeys = jax.vmap(lambda c: R.client_key(rkey, c))(cohort)
        priv_rows = bank.gather(cohort)
        base_frozen = plan.private.frozen(state.variables["params"])

        def one(priv, idx_row, mask_row, key):
            params_c = plan.private.merge(priv, base_frozen)
            vars_c = {**state.variables, "params": params_c}
            out_vars, n_k, msums = self.local_update(
                vars_c, idx_row, mask_row, arrays.x, arrays.y, key
            )
            trained = out_vars["params"]  # adapters + head, pruned
            shared = {
                **{k: v for k, v in out_vars.items() if k != "params"},
                "params": plan.private.frozen(trained),
            }
            return shared, plan.private.trainable(trained), n_k, msums

        stacked_shared, new_priv, n_k, msums = jax.vmap(one)(
            priv_rows, arrays.idx[cohort], arrays.mask[cohort], ckeys
        )

        view = plan.view_state(state)
        if live is not None:
            # elastic: non-live slots healed to the global shared view
            # with zero weight before the screen, like the dense path
            stacked_shared, n_k, msums = E.mask_padded(
                stacked_shared, n_k, msums, view.variables, live
            )
        # the non-finite screen covers BOTH halves of a client's
        # update: a poisoned client contributes nothing to the shared
        # aggregate AND keeps its pre-round bank row (the private twin
        # of the dense path's heal-to-global). Non-live slots are
        # already healed/zero-weight — they are not rejections, and
        # they keep their pre-round rows too.
        ok = robust.finite_client_mask(
            {"shared": stacked_shared, "private": new_priv}, n_k
        )
        lv = jnp.ones(ok.shape, bool) if live is None else live
        ok = ok | ~lv

        def heal(s, g):
            m = ok.reshape((-1,) + (1,) * (s.ndim - 1))
            return jnp.where(m, s, g)

        stacked_shared = jax.tree.map(
            lambda s, g: heal(s, g[None].astype(s.dtype)),
            stacked_shared, view.variables,
        )
        n_k = jnp.where(ok, n_k, jnp.zeros_like(n_k))
        rejected = (ok.shape[0] - jnp.sum(ok)).astype(jnp.float32)

        new_view = server_update(
            cfg, self.cfg.train, self.steps_per_epoch,
            self.batch_size, view, stacked_shared, n_k, rkey,
            local_reducer(), valid=live,
        )
        new_state = plan.merge_state(new_view, state)
        new_bank = bank.put(cohort, new_priv, keep=ok & lv,
                            gathered=priv_rows)
        fin = finalize_sums(jax.tree.map(jnp.sum, msums))
        train_metrics = {
            "train_loss": fin["loss"],
            "train_acc": fin["acc"],
            "nonfinite_rejected": rejected,
        }
        return new_state, train_metrics, new_bank

    def _round(self, state: ServerState, arrays: FederatedArrays,
               n_active=None, residual=None, bank=None):
        if self._bulk.enabled():
            # in bulk mode the residual slot carries the EF
            # ClientStateBank and the bank slot the adapter bank —
            # never both (compress+personalize stays rejected); the
            # python-level dispatch keeps the stacked trace below
            # byte-identical when bulk is off
            return self._bulk_round(
                state, arrays, n_active, ef_bank=residual,
                adapter_bank=bank,
            )
        if bank is not None:
            # personalized PEFT: private adapter bank in, bank out
            # (fedml_tpu.peft.personal; compress+personalize is
            # rejected at construction, so residual is None)
            return self._personal_round(state, arrays, bank, n_active)
        cfg = self.cfg.fed
        stacked_vars, n_k, msums, rkey, cohort = self._locals(
            state, arrays, n_active
        )
        # PEFT: the aggregation half of the round sees the pruned VIEW
        # of the state — deltas, healing, the wire model, and the
        # server step are all O(aggregated subtree); the frozen base is
        # re-merged bitwise at the end (fedml_tpu.peft.partition).
        # Without peft the view IS the state: zero added work.
        view = (
            state if self._peft is None
            else self._peft.view_state(state)
        )

        if self.cfg.adversary.enabled():
            stacked_vars = self._inject_adversaries(
                view, arrays, stacked_vars, cohort
            )
        live = (
            E.active_mask(self._bucket, n_active)
            if n_active is not None else None
        )
        new_residual = None
        if residual is not None:
            # wire order mirrors the deploy path: the client compresses
            # its (possibly adversarial) delta, THEN the server pads /
            # screens what it decompressed
            stacked_vars, new_residual = self._wire_roundtrip(
                view, stacked_vars, residual, rkey, live
            )
        if live is not None:
            # elastic bucketing: the padded slots beyond the live
            # cohort are healed to the global model (delta exactly 0)
            # with zero weight BEFORE screening, so downstream they are
            # indistinguishable from absent — and they must not pollute
            # the round's train metrics either
            stacked_vars, n_k, msums = E.mask_padded(
                stacked_vars, n_k, msums, view.variables, live
            )
        stacked_vars, n_k, rejected = self._screen_nonfinite(
            view, stacked_vars, n_k
        )

        new_state = server_update(
            cfg,
            self.cfg.train,
            self.steps_per_epoch,
            self.batch_size,
            view,
            stacked_vars,
            n_k,
            rkey,
            local_reducer(),
            valid=live,
        )
        if self._peft is not None:
            new_state = self._peft.merge_state(new_state, state)
        reduced = jax.tree.map(jnp.sum, msums)
        fin = finalize_sums(reduced)
        train_metrics = {
            "train_loss": fin["loss"],
            "train_acc": fin["acc"],
            # LAST so rate_bench's first-value sync stays train_loss;
            # consumed host-side by consume_round_counters (the
            # robust.nonfinite_rejected counter)
            "nonfinite_rejected": rejected,
        }
        if new_residual is not None:
            train_metrics["compress_residual_norm"] = T.tree_l2_norm(
                new_residual
            )
            return new_state, train_metrics, new_residual
        return new_state, train_metrics

    def _fused_block(self, state: ServerState, operand, n_active=None,
                     residual=None, bank=None, length: int = 1):
        """``length`` complete rounds as ONE program: a ``lax.scan``
        over the round body with (state[, EF residual / adapter bank])
        as the carry. Each iteration derives its round key from the
        CARRIED ``state.round`` (``_locals`` folds it in), so sampling,
        adversary injection, and the compression quantizer draws are
        bitwise-identical to ``length`` separate ``_round`` calls —
        only XLA's cross-iteration fusion may reassociate float sums
        (the PR-5/PR-7 band, pinned in tests/test_fuse.py). The
        elastic live count is a scan-invariant traced operand: churn
        mid-block is impossible by construction — ``set_cohort_size``
        lands at the next block boundary. Metric leaves stack to
        ``[length, ...]``."""
        if residual is not None:
            def body(carry, _):
                s, res = carry
                s, m, res = self._round_impl(s, operand, n_active, res)
                return (s, res), m

            (state, residual), ms = jax.lax.scan(
                body, (state, residual), None, length=length
            )
            return state, ms, residual
        if bank is not None:
            def body(carry, _):
                s, bk = carry
                s, m, bk = self._round_impl(
                    s, operand, n_active, None, bk
                )
                return (s, bk), m

            (state, bank), ms = jax.lax.scan(
                body, (state, bank), None, length=length
            )
            return state, ms, bank

        def body(carry, _):
            s, m = self._round_impl(carry, operand, n_active)
            return s, m

        state, ms = jax.lax.scan(body, state, None, length=length)
        return state, ms

    def _round_operand(self):
        """Device operand the round body trains from (the sharded
        runtime overrides this with its per-shard banks)."""
        return self.arrays

    def run_block(self, state: ServerState, length: int):
        """Run ``length`` complete rounds as one compiled block
        (:meth:`_fused_block`); returns ``(state, metrics)`` with every
        metric leaf stacked ``[length, ...]``. Requires
        ``FedConfig(fuse_rounds > 1)`` — the block program is built at
        construction. Distinct ``length`` values are distinct compiles
        (``core.fuse.plan_blocks`` keeps the set tiny: the configured K
        plus the remainders eval/checkpoint boundaries force)."""
        if self._block_fn is None:
            raise ValueError(
                "run_block requires FedConfig(fuse_rounds > 1) — the "
                "fused block program is built at construction"
            )
        bulk = self._bulk.enabled()
        compressed = self._cspec.enabled()
        personalized = (
            self._peft is not None and self._peft.personalized
        )
        if personalized:
            self._ensure_adapter_bank(state)
        if compressed:
            if bulk:
                self._ensure_ef_bank(state)
            elif self._ef_residual is None:
                self._ef_residual = C.zero_residual(
                    self._wire_template(state.variables), self._bucket
                )
                telemetry.METRICS.gauge(
                    "compress.ratio",
                    C.wire_ratio(self._cspec,
                                 self._wire_template(state.variables)),
                )
        operand = self._round_operand()
        n = (
            jnp.asarray(self._n_active, jnp.int32)
            if self._elastic else None
        )
        if bulk:
            # nested scans: the outer fused-round scan wraps the inner
            # block scan (the bulk round IS _round_impl's body here);
            # the fused block counts its K rounds so bulk.rounds stays
            # per-round like every fused metric
            self._note_bulk_dispatch(rounds=length)
            if self._stream_defense is not None:
                self._note_stream_defense(state)
            key = self._program_key() + (length,)
        else:
            key = (self._bucket, length)
        res = None
        if compressed:
            res = self._ef_bank if bulk else self._ef_residual

        def call():
            return self._block_fn(
                key, state, operand, n, res,
                self._bank_adapter if personalized else None, length,
            )

        out = (
            E.mirror_jit_cache(self._block_fn, call)
            if self._elastic else call()
        )
        if compressed:
            state, m, new_res = out
            if bulk:
                self._ef_bank = new_res
                SB.note_round_io(
                    length * self._n_blocks
                    * (2 if self._stream_defense else 1),
                    length * self._n_blocks,
                )
            else:
                self._ef_residual = new_res
            return state, m
        if personalized:
            state, m, self._bank_adapter = out
            SB.note_round_io(
                length * (self._n_blocks if bulk else 1),
                length * (self._n_blocks if bulk else 1),
            )
            return state, m
        return out

    def _program_key(self) -> tuple:
        """Executable identity of the bulk round program: the compiled
        block grid. (Only meaningful with the bulk engine on; the
        stacked paths key by bucket as they always have.)"""
        return (self._n_blocks, self._block_size)

    def _note_bulk_dispatch(self, rounds: int = 1) -> None:
        BK.note_round(
            self._block_size, self._n_blocks,
            self._slots - self._n_active, rounds=rounds,
        )

    def _note_stream_defense(self, state: ServerState) -> None:
        """``defense.sketch_*`` gauges at bulk dispatch
        (docs/OBSERVABILITY.md) — one attribute check when off."""
        if not telemetry.METRICS.enabled:
            return
        variables = (
            state.variables if self._peft is None
            else self._peft.view_state(state).variables
        )
        flat_dim = sum(
            int(v.size) for v in jax.tree.leaves(variables["params"])
        )
        SD.note_defense(self._stream_defense, flat_dim, self._slots)

    # -- client-state banks (core/statebank.py) ----------------------------
    @property
    def _adapter_bank(self):
        """Raw ``[num_clients, ...]`` adapter rows (None before the
        first personalized round) — the established surface
        :func:`fedml_tpu.peft.personal.personal_variables` and the
        personalization tests consume; internally the rows live in a
        :class:`~fedml_tpu.core.statebank.ClientStateBank`."""
        b = self._bank_adapter
        return None if b is None else b.rows

    @_adapter_bank.setter
    def _adapter_bank(self, rows):
        self._bank_adapter = (
            None if rows is None
            else SB.ClientStateBank("adapter", rows)
        )

    def _ensure_adapter_bank(self, state: ServerState) -> None:
        """Create the personalization bank LAZILY on the first round
        (from the CURRENT state's init-valued adapters) so that the
        repo's re-call-init()-for-a-snapshot idiom can never reset a
        trained bank mid-run; its lifetime is the simulator's."""
        if self._bank_adapter is not None:
            return
        rows = PP.init_bank(
            self._peft, state.variables["params"],
            self.arrays.num_clients,
        )
        self._bank_adapter = SB.ClientStateBank("adapter", rows)
        telemetry.METRICS.gauge(
            "peft.personal_bank_mb", PP.bank_bytes(rows) / 1e6
        )
        SB.note_bank(self._bank_adapter)

    def _ensure_ef_bank(self, state: ServerState) -> None:
        """Create the bulk-mode error-feedback bank lazily: one zero
        row per CLIENT of the wire template (round 0 transmits the
        uncorrected delta, exactly like the stacked zero carry)."""
        if self._ef_bank is not None:
            return
        self._ef_bank = SB.ClientStateBank.zeros(
            "ef_residual", self._wire_template(state.variables),
            self.arrays.num_clients,
        )
        telemetry.METRICS.gauge(
            "compress.ratio",
            C.wire_ratio(self._cspec,
                         self._wire_template(state.variables)),
        )
        SB.note_bank(self._ef_bank)

    def bank_state(self) -> dict:
        """Client-state banks for the checkpoint composite
        (docs/FAULT_TOLERANCE.md "Client-state banks"): ``{name:
        savable rows}``, empty when no bank has been created yet (a
        fresh run has nothing to save — and nothing to restore)."""
        out = {}
        if self._bank_adapter is not None:
            out[self._bank_adapter.name] = self._bank_adapter.savable()
        if self._ef_bank is not None:
            out[self._ef_bank.name] = self._ef_bank.savable()
        return out

    def restore_banks(self, state: ServerState, blob) -> None:
        """Adopt checkpointed bank rows (the restore half of
        :meth:`bank_state`). A None/empty or legacy blob — or a blob
        from a run without this bank — leaves the lazy fresh-bank init
        in place instead of crashing: the run resumes with round-0
        rows, which is exactly what a pre-bank checkpoint encoded."""
        if not blob:
            return
        if ("adapter" in blob and self._peft is not None
                and self._peft.personalized):
            self._ensure_adapter_bank(state)
            self._bank_adapter = SB.ClientStateBank.from_savable(
                "adapter", self._bank_adapter.rows, blob["adapter"]
            )
        if ("ef_residual" in blob and self._bulk.enabled()
                and self._cspec.enabled()):
            self._ensure_ef_bank(state)
            self._ef_bank = SB.ClientStateBank.from_savable(
                "ef_residual", self._ef_bank.rows, blob["ef_residual"]
            )

    def _wire_template(self, variables):
        """What one client's update payload looks like on the wire:
        the full variables, or the aggregated PEFT subtree — the
        error-feedback residual and the codec accounting are sized by
        this (an O(cohort x adapter) carry under peft, never
        O(cohort x model))."""
        return (
            variables if self._peft is None
            else self._peft.agg_variables(variables)
        )

    def _anatomy_path(self) -> str:
        """The anatomy ring's round-body label (docs/OBSERVABILITY.md
        "Round anatomy"); ``ShardedFedAvg`` overrides it."""
        if self._bulk.enabled():
            return "bulk"
        if self._peft is not None and self._peft.personalized:
            return "personal"
        return "stacked"

    # -- public API --------------------------------------------------------
    def run_round(self, state: ServerState):
        if self._bulk.enabled():
            self._note_bulk_dispatch()
            if self._stream_defense is not None:
                self._note_stream_defense(state)
            key = self._program_key()
            n = (
                jnp.asarray(self._n_active, jnp.int32)
                if self._elastic else None
            )
            if self._peft is not None and self._peft.personalized:
                self._ensure_adapter_bank(state)

                def call():
                    return self._round_fn(
                        key, state, self.arrays, n, None,
                        self._bank_adapter,
                    )

                state, m, self._bank_adapter = (
                    E.mirror_jit_cache(self._round_fn, call)
                    if self._elastic else call()
                )
                SB.note_round_io(self._n_blocks, self._n_blocks)
                return state, m
            if self._cspec.enabled():
                self._ensure_ef_bank(state)

                def call():
                    return self._round_fn(
                        key, state, self.arrays, n, self._ef_bank
                    )

                state, m, self._ef_bank = (
                    E.mirror_jit_cache(self._round_fn, call)
                    if self._elastic else call()
                )
                SB.note_round_io(
                    self._n_blocks
                    * (2 if self._stream_defense else 1),
                    self._n_blocks,
                )
                return state, m
            if not self._elastic:
                return self._round_fn(key, state, self.arrays)
            return E.mirror_jit_cache(
                self._round_fn,
                lambda: self._round_fn(key, state, self.arrays, n),
            )
        if self._peft is not None and self._peft.personalized:
            # the bank is a donated operand and comes back updated —
            # the same thread-through discipline as the EF residual
            self._ensure_adapter_bank(state)
            n = (
                jnp.asarray(self._n_active, jnp.int32)
                if self._elastic else None
            )

            def call():
                return self._round_fn(
                    self._bucket, state, self.arrays, n, None,
                    self._bank_adapter,
                )

            state, m, self._bank_adapter = (
                E.mirror_jit_cache(self._round_fn, call)
                if self._elastic else call()
            )
            SB.note_round_io(1, 1)
            return state, m
        compressed = self._cspec.enabled()
        if compressed and self._ef_residual is None:
            self._ef_residual = C.zero_residual(
                self._wire_template(state.variables), self._bucket
            )
            telemetry.METRICS.gauge(
                "compress.ratio",
                C.wire_ratio(self._cspec,
                             self._wire_template(state.variables)),
            )
        key = self._bucket
        if not self._elastic:
            if not compressed:
                return self._round_fn(key, state, self.arrays)
            state, m, self._ef_residual = self._round_fn(
                key, state, self.arrays, None, self._ef_residual
            )
            return state, m
        # the live count rides as a TRACED operand: any cohort size in
        # [1, bucket] reuses the one compiled program; the ProgramSite
        # is the executable store here
        n = jnp.asarray(self._n_active, jnp.int32)
        if not compressed:
            return E.mirror_jit_cache(
                self._round_fn,
                lambda: self._round_fn(key, state, self.arrays, n),
            )
        state, m, self._ef_residual = E.mirror_jit_cache(
            self._round_fn,
            lambda: self._round_fn(
                key, state, self.arrays, n, self._ef_residual
            ),
        )
        return state, m

    def evaluate_global(self, state: ServerState) -> dict:
        m = self.evaluator(
            state.variables, self.arrays.test_x, self.arrays.test_y
        )
        return {k: float(v) for k, v in m.items()}

    def evaluate_train(self, state: ServerState) -> dict:
        m = self.evaluator(state.variables, self.arrays.x, self.arrays.y)
        return {k: float(v) for k, v in m.items()}

    def run(self, metrics_sink=None) -> ServerState:
        """Round loop (reference ``fedavg_api.train``,
        ``standalone/fedavg/fedavg_api.py:40-81``). With
        ``cfg.fed.profile_rounds > 0`` the perf-observability layer
        (core/perf.py) rides along: jax-profiler capture windows around
        the first K rounds (device-time breakdown) and live ``perf.*``
        gauges — round rate, MFU from the shared analytic cost model,
        and the dispatch-bound detector — for every round. The round
        wall time is taken AFTER the metric host conversion forces the
        device, so it measures execution, not dispatch. With
        ``cfg.fed.fuse_rounds > 1`` the loop advances in fused blocks
        with pipelined host consumption (:meth:`_run_fused`)."""
        import time as _time

        from fedml_tpu.core import perf as P

        state = self.init()
        profiler, monitor = P.build_sim_perf(self)
        try:
            if self._fuse > 1:
                return self._run_fused(
                    state, metrics_sink, profiler, monitor, _time
                )
            # the anatomy plane (core/anatomy.py) attributes phases at
            # sync points this loop ALREADY has — the dispatch return
            # and the one batched device_get below — so the off path
            # stays one attribute check and the on path adds clock
            # reads, never a new device sync
            anat = ANATOMY.enabled
            path = self._anatomy_path()
            for r in range(self.cfg.fed.num_rounds):
                if anat:
                    ANATOMY.begin_round(r, path=path)
                t0 = _time.perf_counter()
                if profiler is not None:
                    profiler.start_round(r)
                state, train_m = self.run_round(state)
                t_disp = _time.perf_counter() if anat else 0.0
                # ONE batched D2H for the whole metric dict instead of
                # a device sync per leaf
                train_m = consume_round_counters(
                    jax.device_get(dict(train_m))
                )
                if anat:
                    # dispatch -> metrics-on-host: the compiled round's
                    # device execution (the sims run the whole round as
                    # one program, so `local` carries it; the dispatch
                    # itself lands in host_gap)
                    ANATOMY.phase(
                        "local", _time.perf_counter() - t_disp
                    )
                record = {
                    "round": r,
                    **{k: float(v) for k, v in train_m.items()},
                }
                if profiler is not None:
                    profiler.end_round(r)
                if monitor is not None:
                    monitor.note_round(_time.perf_counter() - t0)
                if (r + 1) % self.cfg.fed.eval_every == 0 or (
                    r == self.cfg.fed.num_rounds - 1
                ):
                    t_ev = _time.perf_counter() if anat else 0.0
                    test_m = self.evaluate_global(state)
                    if anat:
                        ANATOMY.phase(
                            "eval", _time.perf_counter() - t_ev
                        )
                    record.update(
                        {"test_acc": test_m["acc"],
                         "test_loss": test_m["loss"]}
                    )
                if metrics_sink is not None:
                    metrics_sink.log(record)
                if anat:
                    ANATOMY.end_round()
        finally:
            if profiler is not None:
                profiler.finish()
        return state

    def _run_fused(self, state, metrics_sink, profiler, monitor, _time):
        """Fused round loop (docs/PERFORMANCE.md "Round fusion"):
        advance in blocks of up to ``fuse_rounds`` rounds, keeping
        block k+1's dispatch in flight while the host converts block
        k's stacked metrics (one batched transfer per block), and
        syncing only at eval boundaries and profiler-capture windows.
        The loop itself is ``core.fuse.drive`` (shared with the
        harness's fused loop); boundary placement
        (``core.fuse.plan_blocks``) guarantees eval runs on exactly
        the same round's state as the unfused loop, even when
        ``eval_every % fuse_rounds != 0``."""
        from fedml_tpu.core import fuse as F

        cfg = self.cfg.fed
        box = [state]

        def run_block(length):
            box[0], dm = self.run_block(box[0], length)
            return dm

        def make_records(start, rows):
            return [
                {"round": start + i,
                 **{k: float(v) for k, v in
                    consume_round_counters(row).items()}}
                for i, row in enumerate(rows)
            ]

        def log(rec):
            if metrics_sink is not None:
                metrics_sink.log(rec)

        def boundary_hook(r_last, last):
            if (r_last + 1) % cfg.eval_every == 0 or (
                r_last == cfg.num_rounds - 1
            ):
                anat = ANATOMY.enabled
                t_ev = _time.perf_counter() if anat else 0.0
                test_m = self.evaluate_global(box[0])
                if anat:
                    # the block's anatomy entry closed at the pipeline
                    # flush; the boundary eval amends it
                    ANATOMY.amend_last(
                        "eval", _time.perf_counter() - t_ev
                    )
                last.update({"test_acc": test_m["acc"],
                             "test_loss": test_m["loss"]})
            log(last)

        F.drive(
            run_block,
            F.plan_blocks(0, cfg.num_rounds, self._fuse,
                          cfg.eval_every),
            profiler=profiler,
            monitor=monitor,
            make_records=make_records,
            log=log,
            boundary_hook=boundary_hook,
        )
        return box[0]

"""FedAvg family as one compiled round program.

TPU-native redesign of the reference's standalone simulator
(``fedml_api/standalone/fedavg/fedavg_api.py:40-115``) and the FedOpt /
FedProx / FedNova / robust-aggregation variants — each reference variant is a
configuration of the same compiled round:

- client sampling          (``FedAVGAggregator.client_sampling``)
- vmapped local SGD        (``FedAVGTrainer.train`` x cohort, in parallel)
- weighted pytree mean     (``FedAVGAggregator.aggregate``)
- server optimizer step    (``fedopt/FedOptAggregator`` pseudo-gradient)
- robust preprocessing     (``fedml_core/robustness/robust_aggregation.py``)
- FedNova tau-normalization(``standalone/fednova/fednova.py:97``)

One ``jax.jit`` round; all state device-resident; the python loop only
sequences rounds and reads metrics.
"""

from __future__ import annotations

import math
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.config import ExperimentConfig
from fedml_tpu.core import random as R
from fedml_tpu.core import robust, tree as T
from fedml_tpu.data.federated import FederatedArrays, FederatedData
from fedml_tpu.algorithms.base import (
    build_evaluator,
    build_local_update,
    make_task,
)
from fedml_tpu.models.base import FedModel

Pytree = Any


class ServerState(NamedTuple):
    variables: Pytree  # full model variables (params [+ batch_stats])
    opt_state: Any  # server optimizer state
    momentum: Pytree  # FedNova global momentum buffer
    round: jax.Array  # int32


def make_server_optimizer(name: str, lr: float, momentum: float):
    """Server optimizers (reference ``fedopt/optrepo.py:7`` reflection over
    torch optimizers; ``sgd`` with lr=1 and no momentum == plain FedAvg)."""
    if name == "sgd":
        return optax.sgd(lr, momentum=momentum if momentum else None)
    if name == "adam":
        return optax.adam(lr)
    if name == "adagrad":
        return optax.adagrad(lr)
    if name == "yogi":
        return optax.yogi(lr)
    raise ValueError(f"unknown server optimizer: {name}")


class FedAvgSim:
    """Compiled federated simulation on one chip (see
    :mod:`fedml_tpu.parallel` for the mesh-sharded version)."""

    def __init__(
        self,
        model: FedModel,
        data: FederatedData,
        cfg: ExperimentConfig,
    ):
        self.model = model
        self.cfg = cfg
        self.task = make_task(data.task)
        pad = 1 if cfg.data.full_batch else cfg.data.batch_size
        self.arrays: FederatedArrays = data.to_arrays(pad_multiple=pad)
        max_n = self.arrays.max_client_samples
        self.batch_size = max_n if cfg.data.full_batch else min(
            cfg.data.batch_size, max_n
        )
        self.local_update = build_local_update(
            model, self.task, cfg.train, self.batch_size, max_n
        )
        self.evaluator = build_evaluator(model, self.task)
        self.root_key = jax.random.key(cfg.seed)
        self._round_fn = jax.jit(self._round, donate_argnums=(0,))

    # -- initialization ----------------------------------------------------
    def init(self) -> ServerState:
        variables = self.model.init(
            jax.random.fold_in(self.root_key, 0x7FFFFFFF)
        )
        opt = make_server_optimizer(
            self.cfg.fed.server_optimizer,
            self.cfg.fed.server_lr,
            self.cfg.fed.server_momentum,
        )
        return ServerState(
            variables=variables,
            opt_state=opt.init(variables["params"]),
            momentum=T.tree_zeros_like(variables["params"]),
            round=jnp.asarray(0, jnp.int32),
        )

    # -- one round ---------------------------------------------------------
    def _round(self, state: ServerState, arrays: FederatedArrays):
        cfg = self.cfg.fed
        rkey = R.round_key(self.root_key, state.round)
        cohort = R.sample_clients(
            jax.random.fold_in(rkey, 0),
            arrays.num_clients,
            cfg.clients_per_round,
        )
        ckeys = jax.vmap(lambda c: R.client_key(rkey, c))(cohort)
        idx_rows = arrays.idx[cohort]
        mask_rows = arrays.mask[cohort]

        stacked_vars, n_k, msums = jax.vmap(
            self.local_update, in_axes=(None, 0, 0, None, None, 0)
        )(state.variables, idx_rows, mask_rows, arrays.x, arrays.y, ckeys)

        new_state = self._server_update(state, stacked_vars, n_k, rkey)
        train_metrics = {
            "train_loss": msums["loss_sum"].sum()
            / jnp.maximum(msums["count"].sum(), 1.0),
            "train_acc": msums["correct"].sum()
            / jnp.maximum(msums["count"].sum(), 1.0),
        }
        return new_state, train_metrics

    def _server_update(
        self,
        state: ServerState,
        stacked_vars: Pytree,
        n_k: jax.Array,
        rkey: jax.Array,
    ) -> ServerState:
        cfg = self.cfg.fed
        global_params = state.variables["params"]
        stacked_params = {"params": stacked_vars["params"]}["params"]
        # client deltas (w_k - w_global)
        deltas = jax.tree.map(
            lambda s, g: s - g[None], stacked_params, global_params
        )

        if cfg.robust_norm_clip > 0:
            deltas = robust.clip_deltas_by_norm(deltas, cfg.robust_norm_clip)

        if self.cfg.fed.algorithm == "fednova":
            # tau_k = true local steps; normalize each delta, rescale by
            # tau_eff (reference fednova.py aggregate, tau-normalization)
            steps_pe = self.arrays.max_client_samples // self.batch_size
            tau = (
                jnp.ceil(n_k / self.batch_size).clip(1, steps_pe)
                * self.cfg.train.epochs
            )
            p_k = n_k / jnp.maximum(n_k.sum(), 1.0)
            tau_eff = jnp.sum(p_k * tau)
            d = jax.tree.map(
                lambda x: x / tau.reshape((-1,) + (1,) * (x.ndim - 1)), deltas
            )
            agg_delta = T.tree_scale(T.tree_weighted_mean(d, n_k), tau_eff)
        elif cfg.robust_method == "median":
            agg_delta = robust.coordinate_median(deltas)
        elif cfg.robust_method == "trimmed_mean":
            agg_delta = robust.trimmed_mean(deltas)
        else:
            agg_delta = T.tree_weighted_mean(deltas, n_k)

        if cfg.robust_noise_stddev > 0:
            agg_delta = robust.add_gaussian_noise(
                agg_delta, cfg.robust_noise_stddev, jax.random.fold_in(rkey, 1)
            )

        # server optimizer on the pseudo-gradient -agg_delta
        opt = make_server_optimizer(
            cfg.server_optimizer, cfg.server_lr, cfg.server_momentum
        )
        pseudo_grad = T.tree_scale(agg_delta, -1.0)
        updates, new_opt_state = opt.update(
            pseudo_grad, state.opt_state, global_params
        )
        new_params = optax.apply_updates(global_params, updates)

        # non-param collections (batch_stats): plain weighted mean, like the
        # reference's full-state_dict averaging (FedAVGAggregator.py:73-81)
        other = {
            k: T.tree_weighted_mean(v, n_k)
            for k, v in stacked_vars.items()
            if k != "params"
        }
        new_variables = {**other, "params": new_params}
        return ServerState(
            variables=new_variables,
            opt_state=new_opt_state,
            momentum=state.momentum,
            round=state.round + 1,
        )

    # -- public API --------------------------------------------------------
    def run_round(self, state: ServerState):
        return self._round_fn(state, self.arrays)

    def evaluate_global(self, state: ServerState) -> dict:
        m = self.evaluator(
            state.variables, self.arrays.test_x, self.arrays.test_y
        )
        return {k: float(v) for k, v in m.items()}

    def evaluate_train(self, state: ServerState) -> dict:
        m = self.evaluator(state.variables, self.arrays.x, self.arrays.y)
        return {k: float(v) for k, v in m.items()}

    def run(self, metrics_sink=None) -> ServerState:
        """Round loop (reference ``fedavg_api.train``,
        ``standalone/fedavg/fedavg_api.py:40-81``)."""
        state = self.init()
        for r in range(self.cfg.fed.num_rounds):
            state, train_m = self.run_round(state)
            record = {"round": r, **{k: float(v) for k, v in train_m.items()}}
            if (r + 1) % self.cfg.fed.eval_every == 0 or (
                r == self.cfg.fed.num_rounds - 1
            ):
                test_m = self.evaluate_global(state)
                record.update(
                    {"test_acc": test_m["acc"], "test_loss": test_m["loss"]}
                )
            if metrics_sink is not None:
                metrics_sink.log(record)
        return state

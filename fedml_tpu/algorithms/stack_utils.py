"""Shared helpers for algorithms keeping per-client model stacks.

A "stack" is a pytree whose leaves carry a leading ``[num_clients]`` axis —
the TPU-native representation of the reference's per-client stateful
trainers (``standalone/utils/BaseClient.py:13``). Cohort selection is a
gather, writing results back is a scatter, and per-client evaluation walks
the leading axis.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

Pytree = Any


def stack_gather(stack: Pytree, cohort: jax.Array) -> Pytree:
    return jax.tree.map(lambda s: s[cohort], stack)


def stack_scatter(stack: Pytree, cohort: jax.Array, new: Pytree) -> Pytree:
    return jax.tree.map(lambda s, n: s.at[cohort].set(n), stack, new)


def vmap_init(init_fn: Callable, root_key: jax.Array, n: int) -> Pytree:
    """Independent per-client inits (the reference deep-copies a prototype;
    independent seeds match heterogeneous stateful clients better)."""
    keys = jax.vmap(lambda i: jax.random.fold_in(root_key, i))(jnp.arange(n))
    return jax.vmap(init_fn)(keys)


def evaluate_stack(
    evaluator: Callable, stack: Pytree, test_x, test_y, n: int
) -> dict:
    """Mean per-client metrics on the global test set (reference
    ``_local_test_on_all_clients``,
    ``HeterogeneousModelBaseTrainerAPI.py:82-164``)."""
    accs, losses = [], []
    for i in range(n):
        v = jax.tree.map(lambda s: s[i], stack)
        m = evaluator(v, test_x, test_y)
        accs.append(float(m["acc"]))
        losses.append(float(m["loss"]))
    return {
        "test_acc": sum(accs) / n,
        "test_loss": sum(losses) / n,
        "per_client_acc": accs,
    }


def resolve_cohort_groups(
    requested: int, cohort: int, auto_group_size: int = 5
) -> int:
    """Number of size-sorted sub-groups a cohort runs in.
    ``requested`` is capped at cohort // 2 (a group needs >= 2 clients)
    and rounded DOWN to the nearest divisor of the cohort (static shapes
    need equal groups); 0 = auto -> groups of ``auto_group_size``
    clients. The fused classification cohort measures best at ~5-client
    groups (its fat model's cost scales linearly down to C=5); the
    vmapped GAN path measures best at 2-client groups (FedGDKD 0.93 ->
    1.19 r/s, FedDTG round 1.9x vs static — v5e, idle-machine A/B)."""
    if cohort <= 2:
        return 1
    want = (
        requested if requested > 0
        else max(1, round(cohort / auto_group_size))
    )
    want = max(1, min(want, cohort // 2))
    while cohort % want:
        want -= 1
    return want


def size_grouped_lanes(vcall, lane_args: tuple, mask_rows, requested: int,
                       auto_group_size: int = 2):
    """Run a vmapped per-client update in size-sorted sub-groups.

    ``requested`` is the raw ``TrainConfig.cohort_groups`` value; the
    actual group count is resolved HERE against the true lane count
    (``mask_rows.shape[0]``), so the split always divides the lanes —
    resolving against a config-side client count that disagrees with
    the data's natural client count cannot drop or duplicate lanes.

    Sorting clients by n_k means each sub-group's step-loop cost is set
    by ITS largest member, not the cohort's (for vmapped updates with a
    per-lane dynamic trip count, vmap's batched while runs each call to
    the max over its lanes). Scheduling only: each lane's trajectory
    depends on (globals, its rows, its key) alone.

    ``lane_args`` are pytrees with leading lane axis; every output of
    ``vcall`` must be lane-stacked. Results come back in input order.
    """
    c = mask_rows.shape[0]
    groups = resolve_cohort_groups(requested, c, auto_group_size)
    if groups == 1:
        return vcall(*lane_args)
    assert c % groups == 0, (c, groups)
    sub = c // groups
    order = jnp.argsort(-jnp.sum(mask_rows, axis=1))
    inv = jnp.argsort(order)
    sorted_args = jax.tree.map(lambda a: a[order], lane_args)
    outs = []
    for g in range(groups):
        outs.append(vcall(*jax.tree.map(
            lambda a: a[g * sub:(g + 1) * sub], sorted_args
        )))
    cat = jax.tree.map(lambda *ls: jnp.concatenate(ls, 0), *outs)
    return jax.tree.map(lambda a: a[inv], cat)

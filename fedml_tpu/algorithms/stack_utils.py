"""Shared helpers for algorithms keeping per-client model stacks.

A "stack" is a pytree whose leaves carry a leading ``[num_clients]`` axis —
the TPU-native representation of the reference's per-client stateful
trainers (``standalone/utils/BaseClient.py:13``). Cohort selection is a
gather, writing results back is a scatter, and per-client evaluation walks
the leading axis.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

Pytree = Any


def stack_gather(stack: Pytree, cohort: jax.Array) -> Pytree:
    return jax.tree.map(lambda s: s[cohort], stack)


def stack_scatter(stack: Pytree, cohort: jax.Array, new: Pytree) -> Pytree:
    return jax.tree.map(lambda s, n: s.at[cohort].set(n), stack, new)


def vmap_init(init_fn: Callable, root_key: jax.Array, n: int) -> Pytree:
    """Independent per-client inits (the reference deep-copies a prototype;
    independent seeds match heterogeneous stateful clients better)."""
    keys = jax.vmap(lambda i: jax.random.fold_in(root_key, i))(jnp.arange(n))
    return jax.vmap(init_fn)(keys)


def evaluate_stack(
    evaluator: Callable, stack: Pytree, test_x, test_y, n: int
) -> dict:
    """Mean per-client metrics on the global test set (reference
    ``_local_test_on_all_clients``,
    ``HeterogeneousModelBaseTrainerAPI.py:82-164``)."""
    accs, losses = [], []
    for i in range(n):
        v = jax.tree.map(lambda s: s[i], stack)
        m = evaluator(v, test_x, test_y)
        accs.append(float(m["acc"]))
        losses.append(float(m["loss"]))
    return {
        "test_acc": sum(accs) / n,
        "test_loss": sum(losses) / n,
        "per_client_acc": accs,
    }

"""The fork's GAN-based FL family as compiled round programs.

- :class:`FedGANSim` — federated ACGAN: shared generator + discriminator,
  both FedAvg-aggregated each round (reference
  ``fedml_api/standalone/fedgan/server.py:15-140``,
  ``fedml_api/distributed/fedgan/FedGANAggregator.py:13``).
- :class:`FedGDKDSim` — the fork's thesis algorithm: federated conditional
  generator + per-client (stateful) classifiers; generator-only FedAvg;
  server-synthesized distillation set; leave-one-out mean-teacher logit
  distillation; drift correction for newly-joined clients (reference
  ``fedml_api/standalone/fedgdkd/server.py:70-165``).
- :class:`FedDTGSim` — distributed-GAN variant: shared G + D, per-client
  classifiers trained alongside with gradient reversal; G/D FedAvg;
  leave-one-out distillation on a fake dataset (reference
  ``fedml_api/standalone/fedDTG/server.py:74-133``,
  ``ac_gan_model_trainer.py:63-163``).

TPU design: each round is one jitted program — GAN local updates are
vmapped over the cohort, aggregation is a weighted tree-mean, the
distillation set is generated on device, and per-client logits for the
leave-one-out teacher are a single ``[C, S, K]`` tensor (the mean-teacher
for client i is ``(sum - own) / (C-1)`` — no python loop over clients).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.algorithms import gan_core as G
from fedml_tpu.algorithms.base import (
    build_evaluator,
    make_client_optimizer,
    make_task,
)
from fedml_tpu.config import ExperimentConfig
from fedml_tpu.core import random as R
from fedml_tpu.core import tree as T
from fedml_tpu.data.federated import FederatedArrays, FederatedData, arrays_and_batch
from fedml_tpu.models.base import FedModel
from fedml_tpu.models.gan import GanModel

Pytree = Any


from fedml_tpu.algorithms.stack_utils import (
    evaluate_stack as _evaluate_stack,
    stack_gather as _stack_gather,
    stack_scatter as _stack_scatter,
    vmap_init as _vmap_init,
)
# The GAN step loop's trip count is per-lane dynamic
# (gan_core.build_gan_local_update), and vmap's batched while runs each
# call to the max over ITS lanes — so size_grouped_lanes (shared with
# the classification path, stack_utils) makes small clients stop at
# their own group's maximum instead of the whole cohort's. The group
# count is resolved inside the helper against the true lane count.
from fedml_tpu.algorithms.stack_utils import (
    size_grouped_lanes as _size_grouped_lanes,
)


class FedGANState(NamedTuple):
    gen_vars: Pytree
    disc_vars: Pytree
    round: jax.Array


class FedGANSim:
    """Federated ACGAN: every sampled client adversarially trains the shared
    (G, D) on local data; server averages both weighted by n_k."""

    def __init__(
        self,
        gen: GanModel,
        disc: G.DiscHandle,
        data: FederatedData,
        cfg: ExperimentConfig,
    ):
        self.gen, self.disc, self.cfg = gen, disc, cfg
        self.arrays, self.batch_size = arrays_and_batch(data, cfg.data)
        max_n = self.arrays.max_client_samples

        self.input_shape = self.arrays.x.shape[1:]
        self.local_update = G.build_gan_local_update(
            gen, disc, cfg.train, cfg.gan, self.batch_size, max_n,
            mode="acgan",
        )
        self.root_key = jax.random.key(cfg.seed)
        self._round_fn = jax.jit(self._round, donate_argnums=(0,))

    def init(self) -> FedGANState:
        k = jax.random.fold_in(self.root_key, 0x7FFFFFFF)
        kg, kd = jax.random.split(k)
        return FedGANState(
            gen_vars=self.gen.init(kg),
            disc_vars=self.disc.init(kd, self.input_shape),
            round=jnp.asarray(0, jnp.int32),
        )

    def _round(self, state: FedGANState, arrays: FederatedArrays):
        cfg = self.cfg.fed
        rkey = R.round_key(self.root_key, state.round)
        cohort = R.sample_clients(
            jax.random.fold_in(rkey, 0), arrays.num_clients,
            cfg.clients_per_round,
        )
        ckeys = jax.vmap(lambda c: R.client_key(rkey, c))(cohort)
        mask_rows = arrays.mask[cohort]
        g_stack, d_stack, n_k, sums = _size_grouped_lanes(
            lambda idxs, masks, keys: jax.vmap(
                self.local_update, in_axes=(None, None, 0, 0, None, None, 0)
            )(
                state.gen_vars, state.disc_vars, idxs, masks,
                arrays.x, arrays.y, keys,
            ),
            (arrays.idx[cohort], mask_rows, ckeys), mask_rows,
            self.cfg.train.cohort_groups,
        )
        new_gen = T.tree_weighted_mean(g_stack, n_k)
        new_disc = T.tree_weighted_mean(d_stack, n_k)
        metrics = {
            "g_loss": jnp.sum(sums["g_loss_sum"])
            / jnp.maximum(jnp.sum(sums["batches"]), 1.0),
            "d_loss": jnp.sum(sums["d_loss_sum"])
            / jnp.maximum(jnp.sum(sums["batches"]), 1.0),
        }
        return (
            FedGANState(new_gen, new_disc, state.round + 1),
            metrics,
        )

    def run_round(self, state: FedGANState):
        return self._round_fn(state, self.arrays)

    def sample_images(self, state: FedGANState, n: int, seed: int = 0):
        """Eval-mode image grid (reference ``log_gan_images``,
        ``fedgan/server.py``)."""
        k = jax.random.key(seed)
        z = self.gen.sample_noise(k, n)
        labels = self.gen.balanced_labels(n) if self.gen.conditional else None
        return self.gen.apply_eval(state.gen_vars, z, labels)


class FedGDKDState(NamedTuple):
    gen_vars: Pytree  # global generator (the knowledge vehicle)
    cls_stack: Pytree  # [num_clients, ...] stateful per-client classifiers
    prev_synth_x: jax.Array  # last round's distillation set
    prev_synth_y: jax.Array
    prev_teacher: jax.Array  # mean logits over last round's cohort [S, K]
    prev_sampled: jax.Array  # [num_clients] bool — in last round's cohort
    round: jax.Array


class FedGDKDSim:
    """FedGDKD (the fork's flagship): data-free co-distillation via a
    federated conditional generator; discriminator = each client's own
    classifier (``fedgdkd/server.py:70-165``).

    Per round (all one XLA program):
      1. sample cohort; drift-correct new joiners by KD against last
         round's distillation set + mean teacher (``server.py:92-97``)
      2. vmapped ssgan adversarial training (G synced from global;
         classifier = client's own, persisted) (``model_trainer.py:23-113``)
      3. FedAvg the GENERATOR only, weighted by n_k (``server.py:105-108``)
      4. generate distillation set from averaged G (``server.py:116``)
      5. per-client logits -> leave-one-out mean teacher -> KD
         (``server.py:121-133``)
    """

    def __init__(
        self,
        gen: GanModel,
        classifier: FedModel,
        data: FederatedData,
        cfg: ExperimentConfig,
    ):
        self.gen, self.cfg = gen, cfg
        self.classifier = classifier
        self.disc = G.DiscHandle.from_fed_model(classifier)
        self.arrays, self.batch_size = arrays_and_batch(data, cfg.data)
        max_n = self.arrays.max_client_samples

        self.input_shape = self.arrays.x.shape[1:]
        gan = cfg.gan
        self.synth_size = (
            gan.distillation_size // self.batch_size
        ) * self.batch_size or self.batch_size
        self.local_update = G.build_gan_local_update(
            gen, self.disc, cfg.train, gan, self.batch_size, max_n,
            mode="ssgan",
        )
        self.generate = G.build_dataset_generator(
            gen, self.synth_size, self.batch_size
        )
        self.extract = G.build_logit_extractor(
            self.disc, self.synth_size, self.batch_size
        )
        self.kd_update = G.build_kd_update(
            self.disc, cfg.train, gan, self.synth_size, self.batch_size
        )
        # cohort-fused KD: one grouped network application per synth
        # batch instead of vmapped per-client classifiers (same
        # numerics, far better conv lowering). Used for both KD sites
        # when the classifier/optimizer are eligible.
        from fedml_tpu.algorithms.base import cohort_update_supported

        # the true lane count: sample_clients caps the cohort at the
        # DATA's client count (natural splits can disagree with config)
        kd_cohort = min(
            cfg.fed.clients_per_round, self.arrays.num_clients
        )
        eligible = cfg.train.cohort_fused and cohort_update_supported(
            classifier, cfg.train
        )
        self.cohort_kd = (
            G.build_cohort_kd_update(
                classifier, cfg.train, gan, self.synth_size,
                self.batch_size, kd_cohort,
            )
            if eligible
            else None
        )
        # cohort-fused adversarial phase: grouped generator pyramid +
        # grouped classifier per sub-group (built at the sub-group lane
        # count the size-sorted scheduler will slice)
        from fedml_tpu.algorithms.stack_utils import resolve_cohort_groups

        self._gan_groups = resolve_cohort_groups(
            cfg.train.cohort_groups, kd_cohort, auto_group_size=2
        )
        self.cohort_gan = (
            G.build_cohort_gan_update(
                gen, classifier, cfg.train, gan, self.batch_size, max_n,
                kd_cohort // self._gan_groups,
            )
            if eligible and gen.supports_cohort()
            else None
        )
        self.task = make_task(data.task)
        self.evaluator = build_evaluator(classifier, self.task)
        self.root_key = jax.random.key(cfg.seed)
        self._round_fn = jax.jit(self._round, donate_argnums=(0,))

    def _run_kd(self, cls_vars, synth_x, synth_y, teachers, keys):
        """Single dispatch point for both KD sites (drift correction +
        leave-one-out distillation): the cohort-fused update when
        eligible, else vmapped per-client kd. ``teachers`` is always
        [C, S, K] (broadcast the shared mean teacher for drift
        correction)."""
        if self.cohort_kd is not None:
            return self.cohort_kd(
                cls_vars, synth_x, synth_y, teachers, keys
            )
        return jax.vmap(
            self.kd_update, in_axes=(0, None, None, 0, 0)
        )(cls_vars, synth_x, synth_y, teachers, keys)

    def init(self) -> FedGDKDState:
        k = jax.random.fold_in(self.root_key, 0x7FFFFFFF)
        kg, kc = jax.random.split(k)
        n = self.arrays.num_clients
        cls_stack = _vmap_init(self.classifier.init, kc, n)
        num_classes = self.arrays.num_classes
        return FedGDKDState(
            gen_vars=self.gen.init(kg),
            cls_stack=cls_stack,
            prev_synth_x=jnp.zeros(
                (self.synth_size,) + tuple(self.input_shape), jnp.float32
            ),
            prev_synth_y=jnp.zeros((self.synth_size,), jnp.int32),
            prev_teacher=jnp.zeros((self.synth_size, num_classes)),
            prev_sampled=jnp.zeros((n,), bool),
            round=jnp.asarray(0, jnp.int32),
        )

    def _round(self, state: FedGDKDState, arrays: FederatedArrays):
        cfg = self.cfg.fed
        rkey = R.round_key(self.root_key, state.round)
        cohort = R.sample_clients(
            jax.random.fold_in(rkey, 0), arrays.num_clients,
            cfg.clients_per_round,
        )
        ckeys = jax.vmap(lambda c: R.client_key(rkey, c))(cohort)
        cls_vars = _stack_gather(state.cls_stack, cohort)

        # 1. drift correction: KD for cohort members NOT sampled last round
        #    (server.py:92-97; no-op in round 0)
        is_new = jnp.logical_and(
            state.round > 0, ~state.prev_sampled[cohort]
        )

        def do_correct(cls_vars):
            dkeys = jax.vmap(
                lambda k: jax.random.fold_in(k, 0xD1F7)
            )(ckeys)
            corrected, _ = self._run_kd(
                cls_vars, state.prev_synth_x, state.prev_synth_y,
                jnp.broadcast_to(
                    state.prev_teacher[None],
                    (dkeys.shape[0],) + state.prev_teacher.shape,
                ),
                dkeys,
            )
            return jax.tree.map(
                lambda new, old: jnp.where(
                    is_new.reshape((-1,) + (1,) * (new.ndim - 1)), new, old
                ),
                corrected, cls_vars,
            )

        # skip the whole KD pass when the cohort has no new joiners (the
        # steady-state/full-participation common case)
        cls_vars = jax.lax.cond(
            jnp.any(is_new), do_correct, lambda v: v, cls_vars
        )

        # 2. adversarial co-training (generator from global), scheduled
        #    in size-sorted sub-groups so small clients' step loops stop
        #    at their own group's trip count. The fused path runs each
        #    sub-group as ONE grouped generator + classifier network.
        mask_rows = arrays.mask[cohort]
        if self.cohort_gan is not None:
            inner = lambda cvars, idxs, masks, keys: self.cohort_gan(
                state.gen_vars, cvars, idxs, masks,
                arrays.x, arrays.y, keys,
            )
            requested = self._gan_groups
        else:
            inner = lambda cvars, idxs, masks, keys: jax.vmap(
                self.local_update, in_axes=(None, 0, 0, 0, None, None, 0)
            )(
                state.gen_vars, cvars, idxs, masks,
                arrays.x, arrays.y, keys,
            )
            requested = self.cfg.train.cohort_groups
        g_stack, cls_vars, n_k, sums = _size_grouped_lanes(
            inner, (cls_vars, arrays.idx[cohort], mask_rows, ckeys),
            mask_rows, requested,
        )

        # 3. generator-only FedAvg (server.py:105-108)
        new_gen = T.tree_weighted_mean(g_stack, n_k)

        # 4. distillation set from the averaged generator (server.py:116)
        synth_x, synth_y = self.generate(
            new_gen, jax.random.fold_in(rkey, 0x5EED)
        )

        # 5. leave-one-out mean-teacher KD (server.py:121-133)
        logits = jax.vmap(self.extract, in_axes=(0, None))(
            cls_vars, synth_x
        )  # [C, S, K]
        c = logits.shape[0]
        loo_teacher = (jnp.sum(logits, 0)[None] - logits) / jnp.maximum(
            c - 1, 1
        )
        kd_keys = jax.vmap(lambda k: jax.random.fold_in(k, 0xAD))(ckeys)
        cls_vars, kd_losses = self._run_kd(
            cls_vars, synth_x, synth_y, loo_teacher, kd_keys
        )

        new_stack = _stack_scatter(state.cls_stack, cohort, cls_vars)
        new_sampled = (
            jnp.zeros_like(state.prev_sampled).at[cohort].set(True)
        )
        metrics = {
            "g_loss": jnp.sum(sums["g_loss_sum"])
            / jnp.maximum(jnp.sum(sums["batches"]), 1.0),
            "d_loss": jnp.sum(sums["d_loss_sum"])
            / jnp.maximum(jnp.sum(sums["batches"]), 1.0),
            "kd_loss": jnp.sum(kd_losses["kd_loss_sum"])
            / jnp.maximum(jnp.sum(kd_losses["batches"]), 1.0),
        }
        new_state = FedGDKDState(
            gen_vars=new_gen,
            cls_stack=new_stack,
            prev_synth_x=synth_x,
            prev_synth_y=synth_y,
            prev_teacher=jnp.mean(logits, 0),
            prev_sampled=new_sampled,
            round=state.round + 1,
        )
        return new_state, metrics

    def run_round(self, state: FedGDKDState):
        return self._round_fn(state, self.arrays)

    def evaluate_clients(self, state: FedGDKDState) -> dict:
        return _evaluate_stack(
            self.evaluator, state.cls_stack, self.arrays.test_x,
            self.arrays.test_y, self.arrays.num_clients,
        )

    def run(self, metrics_sink=None) -> FedGDKDState:
        state = self.init()
        for r in range(self.cfg.fed.num_rounds):
            state, m = self.run_round(state)
            record = {"round": r, **{k: float(v) for k, v in m.items()}}
            if (r + 1) % self.cfg.fed.eval_every == 0 or (
                r == self.cfg.fed.num_rounds - 1
            ):
                ev = self.evaluate_clients(state)
                record.update(
                    {"test_acc": ev["test_acc"], "test_loss": ev["test_loss"]}
                )
            if metrics_sink is not None:
                metrics_sink.log(record)
        return state


@jax.custom_vjp
def reverse_grad(x):
    """Gradient-reversal (FedDTG's ``register_hook(lambda g: -g)``,
    ``fedDTG/ac_gan_model_trainer.py:108``)."""
    return x


def _rg_fwd(x):
    return x, None


def _rg_bwd(_, g):
    return (jax.tree.map(jnp.negative, g),)


reverse_grad.defvjp(_rg_fwd, _rg_bwd)


class FedDTGState(NamedTuple):
    gen_vars: Pytree
    disc_vars: Pytree
    cls_stack: Pytree
    round: jax.Array


class FedDTGSim:
    """FedDTG: shared (G, D) + per-client classifiers; GAN steps use a
    dedicated validity-only D with soft real label 0.9, the classifier
    co-trains on real+fake, and G receives a REVERSED gradient through the
    classifier (``fedDTG/ac_gan_model_trainer.py:63-163``). After G/D
    FedAvg, classifiers distill leave-one-out on a generated fake set
    (``fedDTG/server.py:108-133``)."""

    REAL_LABEL = 0.9

    def __init__(
        self,
        gen: GanModel,
        disc: G.DiscHandle,
        classifier: FedModel,
        data: FederatedData,
        cfg: ExperimentConfig,
    ):
        self.gen, self.disc, self.cfg = gen, disc, cfg
        self.classifier = classifier
        self.cls_handle = G.DiscHandle.from_fed_model(classifier)
        self.arrays, self.batch_size = arrays_and_batch(data, cfg.data)
        self.max_n = self.arrays.max_client_samples
        self.input_shape = self.arrays.x.shape[1:]
        self.synth_size = (
            cfg.gan.distillation_size // self.batch_size
        ) * self.batch_size or self.batch_size
        self.generate = G.build_dataset_generator(
            gen, self.synth_size, self.batch_size
        )
        self.extract = G.build_logit_extractor(
            self.cls_handle, self.synth_size, self.batch_size
        )
        self.kd_update = G.build_kd_update(
            self.cls_handle, cfg.train, cfg.gan, self.synth_size,
            self.batch_size,
        )
        self.task = make_task(data.task)
        self.evaluator = build_evaluator(classifier, self.task)
        self.root_key = jax.random.key(cfg.seed)
        self.local_update = self._build_local_update()
        self._round_fn = jax.jit(self._round, donate_argnums=(0,))

    def _build_local_update(self):
        gen, disc, cls = self.gen, self.disc, self.cls_handle
        cfg_t, cfg_g = self.cfg.train, self.cfg.gan
        batch_size, max_n = self.batch_size, self.max_n
        steps_per_epoch = max_n // batch_size
        g_opt = G.make_gen_optimizer(cfg_g)
        d_opt = G.make_gen_optimizer(cfg_g)  # D follows gen optimizer here
        c_opt = make_client_optimizer(cfg_t)

        def g_loss_fn(g_params, g_static, d_vars, c_vars, z, gl, w, rng):
            g_vars = {**g_static, "params": g_params}
            fakes, new_g = gen.apply_train(g_vars, z, gl)
            (_, val), _ = disc.apply_train(d_vars, fakes, rng, validity=True)
            pred, _ = cls.apply_train(c_vars, fakes, rng)
            pred = reverse_grad(pred)  # :108 gradient reversal
            adv = G._bce_logits(val, jnp.full(val.shape[0], self.REAL_LABEL), w)
            aux = G._ce(pred, gl, w)
            return 0.5 * (adv + aux), (new_g, fakes)

        def d_loss_fn(d_params, d_static, fakes, x_b, w, rng):
            d_vars = {**d_static, "params": d_params}
            r1, r2 = jax.random.split(rng)
            (_, v_r), d1 = disc.apply_train(d_vars, x_b, r1, validity=True)
            (_, v_f), d2 = disc.apply_train(d1, fakes, r2, validity=True)
            loss = 0.5 * (
                G._bce_logits(v_r, jnp.full(v_r.shape[0], self.REAL_LABEL), w)
                + G._bce_logits(v_f, jnp.zeros(v_f.shape[0]), w)
            )
            return loss, d2

        def c_loss_fn(c_params, c_static, fakes, gl, x_b, y_b, w, rng):
            c_vars = {**c_static, "params": c_params}
            r1, r2 = jax.random.split(rng)
            p_real, c1 = cls.apply_train(c_vars, x_b, r1)
            p_fake, c2 = cls.apply_train(c1, fakes, r2)
            loss = 0.5 * (G._ce(p_real, y_b, w) + G._ce(p_fake, gl, w))
            return loss, c2

        g_grad = jax.value_and_grad(g_loss_fn, has_aux=True)
        d_grad = jax.value_and_grad(d_loss_fn, has_aux=True)
        c_grad = jax.value_and_grad(c_loss_fn, has_aux=True)

        def update(gen_vars, disc_vars, cls_vars, idx_row, mask_row, x, y, rng):
            def epoch_body(carry, ekey):
                g_vars, d_vars, c_vars, g_os, d_os, c_os = carry
                perm = jax.random.permutation(ekey, max_n)
                order = jnp.argsort(1.0 - mask_row[perm], stable=True)
                perm = perm[order]

                def step_body(carry2, step):
                    g_vars, d_vars, c_vars, g_os, d_os, c_os = carry2
                    take = jax.lax.dynamic_slice_in_dim(
                        perm, step * batch_size, batch_size
                    )
                    b_idx = idx_row[take]
                    w_b = mask_row[take]
                    x_b = jnp.take(x, b_idx, axis=0)
                    y_b = jnp.take(y, b_idx, axis=0)
                    skey = jax.random.fold_in(ekey, step)
                    kz, kl, k1, k2, k3 = jax.random.split(skey, 5)
                    z = gen.sample_noise(kz, batch_size)
                    gl = gen.sample_labels(kl, batch_size)

                    gp = g_vars["params"]
                    gs = {k: v for k, v in g_vars.items() if k != "params"}
                    (_, (new_g, fakes)), ggr = g_grad(
                        gp, gs, d_vars, c_vars, z, gl, w_b, k1
                    )
                    gu, new_g_os = g_opt.update(ggr, g_os, gp)
                    new_g = {**new_g, "params": optax.apply_updates(gp, gu)}

                    fakes = jax.lax.stop_gradient(fakes)
                    dp = d_vars["params"]
                    ds = {k: v for k, v in d_vars.items() if k != "params"}
                    (_, new_d), dgr = d_grad(dp, ds, fakes, x_b, w_b, k2)
                    du, new_d_os = d_opt.update(dgr, d_os, dp)
                    new_d = {**new_d, "params": optax.apply_updates(dp, du)}

                    cp = c_vars["params"]
                    cs = {k: v for k, v in c_vars.items() if k != "params"}
                    (_, new_c), cgr = c_grad(
                        cp, cs, fakes, gl, x_b, y_b, w_b, k3
                    )
                    cu, new_c_os = c_opt.update(cgr, c_os, cp)
                    new_c = {**new_c, "params": optax.apply_updates(cp, cu)}

                    valid = jnp.sum(w_b) > 0
                    sel = lambda n, o: jax.tree.map(
                        lambda a, b: jnp.where(valid, a, b), n, o
                    )
                    return (
                        sel(new_g, g_vars), sel(new_d, d_vars),
                        sel(new_c, c_vars), sel(new_g_os, g_os),
                        sel(new_d_os, d_os), sel(new_c_os, c_os),
                    )

                n_steps = G.dynamic_trip_count(
                    mask_row, batch_size, steps_per_epoch
                )
                carry = jax.lax.fori_loop(
                    0, n_steps, lambda i, c: step_body(c, i),
                    (g_vars, d_vars, c_vars, g_os, d_os, c_os),
                )
                return carry, None

            g_os = g_opt.init(gen_vars["params"])
            d_os = d_opt.init(disc_vars["params"])
            c_os = c_opt.init(cls_vars["params"])
            ekeys = jax.vmap(lambda e: jax.random.fold_in(rng, e))(
                jnp.arange(cfg_t.epochs)
            )
            (g_vars, d_vars, c_vars, _, _, _), _ = jax.lax.scan(
                epoch_body,
                (gen_vars, disc_vars, cls_vars, g_os, d_os, c_os),
                ekeys,
            )
            return g_vars, d_vars, c_vars, jnp.sum(mask_row)

        return update

    def init(self) -> FedDTGState:
        k = jax.random.fold_in(self.root_key, 0x7FFFFFFF)
        kg, kd, kc = jax.random.split(k, 3)
        return FedDTGState(
            gen_vars=self.gen.init(kg),
            disc_vars=self.disc.init(kd, self.input_shape),
            cls_stack=_vmap_init(
                self.classifier.init, kc, self.arrays.num_clients
            ),
            round=jnp.asarray(0, jnp.int32),
        )

    def _round(self, state: FedDTGState, arrays: FederatedArrays):
        cfg = self.cfg.fed
        rkey = R.round_key(self.root_key, state.round)
        cohort = R.sample_clients(
            jax.random.fold_in(rkey, 0), arrays.num_clients,
            cfg.clients_per_round,
        )
        ckeys = jax.vmap(lambda c: R.client_key(rkey, c))(cohort)
        cls_vars = _stack_gather(state.cls_stack, cohort)

        mask_rows = arrays.mask[cohort]
        g_stack, d_stack, cls_vars, n_k = _size_grouped_lanes(
            lambda cvars, idxs, masks, keys: jax.vmap(
                self.local_update,
                in_axes=(None, None, 0, 0, 0, None, None, 0),
            )(
                state.gen_vars, state.disc_vars, cvars, idxs, masks,
                arrays.x, arrays.y, keys,
            ),
            (cls_vars, arrays.idx[cohort], mask_rows, ckeys), mask_rows,
            self.cfg.train.cohort_groups,
        )
        new_gen = T.tree_weighted_mean(g_stack, n_k)
        new_disc = T.tree_weighted_mean(d_stack, n_k)

        synth_x, synth_y = self.generate(
            new_gen, jax.random.fold_in(rkey, 0x5EED)
        )
        logits = jax.vmap(self.extract, in_axes=(0, None))(cls_vars, synth_x)
        c = logits.shape[0]
        loo = (jnp.sum(logits, 0)[None] - logits) / jnp.maximum(c - 1, 1)
        cls_vars, kd_losses = jax.vmap(
            self.kd_update, in_axes=(0, None, None, 0, 0)
        )(
            cls_vars, synth_x, synth_y, loo,
            # distinct fold so the KD key stream cannot collide with the
            # adversarial phase's (which already consumed ckeys)
            jax.vmap(lambda k: jax.random.fold_in(k, 0xAD))(ckeys),
        )

        new_state = FedDTGState(
            gen_vars=new_gen,
            disc_vars=new_disc,
            cls_stack=_stack_scatter(state.cls_stack, cohort, cls_vars),
            round=state.round + 1,
        )
        metrics = {
            "kd_loss": jnp.sum(kd_losses["kd_loss_sum"])
            / jnp.maximum(jnp.sum(kd_losses["batches"]), 1.0),
        }
        return new_state, metrics

    def run_round(self, state: FedDTGState):
        return self._round_fn(state, self.arrays)

    def evaluate_clients(self, state: FedDTGState) -> dict:
        return _evaluate_stack(
            self.evaluator, state.cls_stack, self.arrays.test_x,
            self.arrays.test_y, self.arrays.num_clients,
        )

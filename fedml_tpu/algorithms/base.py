"""Task definitions and the compiled client local-update.

The reference's per-task trainers
(``fedml_api/standalone/fedavg/my_model_trainer_classification.py``,
``..._nwp.py``, ``..._tag_prediction.py``) become pure loss/metric functions
here, and ``MyModelTrainer.train`` (epochs x minibatch SGD) becomes a jitted
``lax.scan`` over steps that is *vmapped across the cohort* — one XLA
program trains every sampled client in parallel on the MXU.

Padding discipline: every client's index row is padded to ``max_n``; a
padded batch contributes zero gradient AND zero optimizer-state update
(updates are gated on the batch containing at least one real sample), so a
small client's trajectory exactly matches serial training on its real data.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.config import TrainConfig
from fedml_tpu.core import tree as T
from fedml_tpu.models.base import FedModel

Pytree = Any


# ---------------------------------------------------------------------------
# Tasks (loss + metrics)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Task:
    """Sufficient-statistics metrics for one task type.

    ``metric_sums(logits, y, w)`` returns additive SUMS:
    ``loss_sum`` (weighted loss numerator), ``w_sum`` (loss denominator),
    ``correct`` / ``count`` (accuracy numerator / denominator — for the tag
    task these are micro-precision TP / predicted-positives). Reduce sums
    across batches/clients/shards first, then call :func:`finalize_sums`.
    """

    name: str
    metric_sums: Callable[[jax.Array, jax.Array, jax.Array], dict]


def zero_sums() -> dict:
    return {
        "loss_sum": jnp.asarray(0.0),
        "correct": jnp.asarray(0.0),
        "count": jnp.asarray(0.0),
        "w_sum": jnp.asarray(0.0),
    }


def finalize_sums(sums: dict) -> dict:
    """Turn reduced metric sums into {loss, acc}. Clamps are applied ONCE
    here, after the final reduction, so per-batch zero-prediction batches
    don't distort micro-precision."""
    return {
        "loss": sums["loss_sum"] / jnp.maximum(sums["w_sum"], 1.0),
        "acc": sums["correct"] / jnp.maximum(sums["count"], 1.0),
    }


def _classification_task() -> Task:
    def sums(logits, y, w):
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        correct = (jnp.argmax(logits, -1) == y).astype(jnp.float32)
        return {
            "loss_sum": jnp.sum(ce * w),
            "correct": jnp.sum(correct * w),
            "count": jnp.sum(w),
            "w_sum": jnp.sum(w),
        }

    return Task("classification", sums)


def _nwp_task() -> Task:
    """Next-word/char prediction: logits [B,T,V], y [B,T]; token-level
    accuracy (reference ``my_model_trainer_nwp.py``)."""

    def sums(logits, y, w):
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        correct = (jnp.argmax(logits, -1) == y).astype(jnp.float32)
        tokens = jnp.sum(w) * y.shape[1]
        return {
            "loss_sum": jnp.sum(ce * w[:, None]),
            "correct": jnp.sum(correct * w[:, None]),
            "count": tokens,
            "w_sum": tokens,
        }

    return Task("nwp", sums)


def _tag_task() -> Task:
    """Multi-label tag prediction with sigmoid BCE; accuracy = micro
    precision at threshold 0.5 (reference multilabel path,
    ``fedml_core/trainer/model_trainer.py:57-112``)."""

    def sums(logits, y, w):
        bce = optax.sigmoid_binary_cross_entropy(logits, y).mean(-1)
        pred = (jax.nn.sigmoid(logits) > 0.5).astype(jnp.float32)
        tp = jnp.sum(pred * y * w[:, None])
        predicted = jnp.sum(pred * w[:, None])
        return {
            "loss_sum": jnp.sum(bce * w),
            "correct": tp,  # micro-precision numerator
            "count": predicted,  # micro-precision denominator (raw sum)
            "w_sum": jnp.sum(w),
        }

    return Task("tag_prediction", sums)


def _segmentation_task() -> Task:
    """Per-pixel CE for semantic segmentation: logits [B,H,W,K], y [B,H,W];
    accuracy = pixel accuracy (reference fedseg ``MyModelTrainer`` CE loss +
    ``Evaluator.Pixel_Accuracy``, ``fedseg/utils.py:251``). mIoU/FWIoU come
    from :class:`fedml_tpu.metrics.segmentation.SegEvaluator`."""

    def sums(logits, y, w):
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        ce = ce.mean(axis=(1, 2))  # per-image mean over pixels
        correct = (jnp.argmax(logits, -1) == y).astype(jnp.float32)
        pixels = y.shape[1] * y.shape[2]
        return {
            "loss_sum": jnp.sum(ce * w),
            "correct": jnp.sum(correct.mean(axis=(1, 2)) * w * pixels),
            "count": jnp.sum(w) * pixels,
            "w_sum": jnp.sum(w),
        }

    return Task("segmentation", sums)


def make_task(name: str) -> Task:
    return {
        "classification": _classification_task,
        "nwp": _nwp_task,
        "tag_prediction": _tag_task,
        "segmentation": _segmentation_task,
    }[name]()


# ---------------------------------------------------------------------------
# Client optimizer
# ---------------------------------------------------------------------------


def make_client_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    """Reference client optimizers: SGD(momentum, wd) or Adam(wd, amsgrad)
    (``my_model_trainer_classification.py`` train())."""
    chain = []
    if cfg.clip_norm > 0:
        chain.append(optax.clip_by_global_norm(cfg.clip_norm))
    if cfg.optimizer == "sgd":
        if cfg.weight_decay > 0:
            chain.append(optax.add_decayed_weights(cfg.weight_decay))
        chain.append(
            optax.sgd(cfg.lr, momentum=cfg.momentum if cfg.momentum else None)
        )
    elif cfg.optimizer == "adam":
        chain.append(optax.adamw(cfg.lr, weight_decay=cfg.weight_decay))
    else:
        raise ValueError(f"unknown client optimizer: {cfg.optimizer}")
    return optax.chain(*chain)


# ---------------------------------------------------------------------------
# Mixed-precision casting policy (shared by both local-update builders)
# ---------------------------------------------------------------------------


def _tree_to_dtype(t: Pytree, dtype) -> Pytree:
    """Cast float leaves to the compute dtype (mixed precision: master
    params and optimizer state stay f32, the network runs in bf16 — grads
    flow back through the cast as f32)."""
    cast = lambda a: (
        a.astype(dtype) if jnp.issubdtype(a.dtype, jnp.floating) else a
    )
    return jax.tree.map(cast, t)


def _static_vars_to_dtype(static_vars: dict, dtype) -> dict:
    """batch_stats stay f32: the BN running-statistic EMA has relative
    updates below bf16 resolution (momentum 0.99 -> 1% steps), so
    quantizing the accumulator would freeze it. Flax computes the EMA in
    the stats' own dtype — keeping the stored stats f32 keeps the
    accumulation exact while activations run bf16."""
    return {
        k: (v if k == "batch_stats" else _tree_to_dtype(v, dtype))
        for k, v in static_vars.items()
    }


def _tree_floats_back(t: Pytree, compute_dtype) -> Pytree:
    cast = lambda a: (
        a.astype(jnp.float32) if a.dtype == compute_dtype else a
    )
    return jax.tree.map(cast, t)


# ---------------------------------------------------------------------------
# Local update (the client hot loop, compiled)
# ---------------------------------------------------------------------------


def _padded_perm(ekey: jax.Array, mask_row: jax.Array, max_n: int):
    """One epoch's batch order for one client: shuffle, then stable-sort
    so real samples occupy the first ceil(n_k/B) batches (shuffled among
    themselves) and trailing batches are fully padding. A small client
    thus takes exactly its serial-equivalent number of optimizer steps
    instead of scattering 1-2 real samples into many full-lr steps — and
    FedNova's tau = ceil(n_k/B)*epochs stays exact. SHARED by the vmapped
    and cohort-fused local updates: their trajectory equality depends on
    this ordering being identical."""
    perm = jax.random.permutation(ekey, max_n)
    order = jnp.argsort(1.0 - mask_row[perm], stable=True)
    return perm[order]


def build_local_update(
    model: FedModel,
    task: Task,
    cfg: TrainConfig,
    batch_size: int,
    max_n: int,
    data_axis: str | None = None,
    data_axis_size: int = 1,
    partition=None,
):
    """Build ``local_update(global_vars, idx_row, mask_row, x, y, rng)``.

    Replaces ``MyModelTrainer.train`` (reference
    ``standalone/fedavg/my_model_trainer_classification.py``): runs
    ``cfg.epochs`` passes of minibatch SGD over the client's (padded) data,
    returns ``(new_vars, n_k, train_metric_sums)``.

    ``batch_size`` and ``max_n`` are static; ``max_n`` must be a multiple of
    ``batch_size`` (the padder guarantees it). The whole function is pure and
    vmappable over the leading axis of (idx_row, mask_row, rng).

    If ``data_axis`` is set, the function must run inside a ``shard_map``
    over a mesh axis of that name: each shard consumes a disjoint
    ``batch_size // data_axis_size`` slice of every batch and gradients are
    ``psum``-ed — the TPU analog of the reference's intra-silo DDP
    (``fedavg_cross_silo/DistWorker.py:52-54``, NCCL allreduce per batch).

    ``partition`` (a :class:`fedml_tpu.peft.partition.ParamPartition`)
    restricts training to the TRAINABLE params subtree: gradients,
    optimizer state, the scan carry, and the RETURNED ``new_vars["params"]``
    all live at O(trainable) — the frozen base is closed over as a
    constant (it reaches the forward via a structural merge that costs
    nothing at runtime), takes no optimizer step, and never appears in
    the client's update. With ``partition=None`` (the default) every
    code path below is byte-identical to its pre-PEFT self.
    """
    assert max_n % batch_size == 0, (max_n, batch_size)
    assert batch_size % data_axis_size == 0, (batch_size, data_axis_size)
    steps_per_epoch = max_n // batch_size
    shard_bs = batch_size // data_axis_size
    opt = make_client_optimizer(cfg)
    compute_dtype = jnp.dtype(cfg.compute_dtype)
    mixed = compute_dtype != jnp.float32

    _to_compute = lambda t: _tree_to_dtype(t, compute_dtype)
    _to_compute_vars = lambda sv: _static_vars_to_dtype(sv, compute_dtype)
    _to_f32 = lambda t: _tree_floats_back(t, compute_dtype)

    def loss_fn(params, static_vars, x_b, y_b, w_b, rng, global_params,
                frozen_params=None):
        """Weighted-SUM loss normalized by the psum-ed weight total, so that
        psum of per-shard grads equals the exact full-batch gradient even
        with masked (padded) samples. Under a partition ``params`` is the
        trainable subtree only; the frozen base merges in structurally
        (grads flow to the trainable leaves alone)."""
        if frozen_params is not None:
            params = partition.merge(params, frozen_params)
        if mixed:
            variables = {
                **_to_compute_vars(static_vars),
                "params": _to_compute(params),
            }
            x_b = _to_compute(x_b)
        else:
            variables = {**static_vars, "params": params}
        logits, new_vars = model.apply_train(variables, x_b, rng)
        if mixed:
            logits = logits.astype(jnp.float32)
            new_vars = _to_f32(new_vars)
        sums = task.metric_sums(logits, y_b, w_b)
        w_total = sums["w_sum"]
        if data_axis is not None:
            w_total = jax.lax.psum(w_total, data_axis)
        loss = sums["loss_sum"] / jnp.maximum(w_total, 1.0)
        if cfg.prox_mu > 0:  # FedProx proximal term (fedprox trainer)
            diff = T.tree_sub(params, global_params)
            loss = loss + 0.5 * cfg.prox_mu * T.tree_dot(diff, diff) / (
                data_axis_size
            )
        return loss, (new_vars, sums)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def local_update(global_vars, idx_row, mask_row, x, y, rng):
        global_params = global_vars["params"]
        if partition is not None:
            # frozen base: a per-round constant captured here, NOT part
            # of the scan carry or the optimizer state — under
            # vmap(local_update, in_axes=(None, ...)) it stays unbatched,
            # so no [C, model] copy of the base ever materializes
            frozen_params = partition.frozen(global_params)
            start_params = partition.trainable(global_params)
        else:
            frozen_params = None
            start_params = global_params
        start_vars = {
            **{k: v for k, v in global_vars.items() if k != "params"},
            "params": start_params,
        }

        def epoch_body(carry, ekey):
            variables, opt_state, msums = carry
            perm = _padded_perm(ekey, mask_row, max_n)

            def step_body(carry2, step):
                variables, opt_state, msums = carry2
                offset = step * batch_size
                if data_axis is not None:
                    offset = offset + jax.lax.axis_index(data_axis) * shard_bs
                take = jax.lax.dynamic_slice_in_dim(perm, offset, shard_bs)
                b_idx = idx_row[take]
                w_b = mask_row[take]
                x_b = jnp.take(x, b_idx, axis=0)
                y_b = jnp.take(y, b_idx, axis=0)
                skey = jax.random.fold_in(ekey, step)
                params = variables["params"]
                static_vars = {
                    k: v for k, v in variables.items() if k != "params"
                }
                (_, (new_vars, sums)), grads = grad_fn(
                    params, static_vars, x_b, y_b, w_b, skey,
                    global_params, frozen_params,
                )
                if data_axis is not None:
                    grads = jax.lax.psum(grads, data_axis)
                    sums = jax.tree.map(
                        lambda s: jax.lax.psum(s, data_axis), sums
                    )
                    # keep batch_stats consistent across the data axis
                    # (sync-BN-lite; reference uses SynchronizedBatchNorm
                    # for fedseg, batchnorm_utils.py:240). For EXACT
                    # synchronized moments use a model built with
                    # ModelConfig(extra=(("norm", "syncbn:<data_axis>"),))
                    # — models.vision.SyncBatchNorm psums the batch
                    # statistics inside the forward; this pmean is then a
                    # no-op on its already-identical stats.
                    new_vars = {
                        k: (
                            jax.lax.pmean(v, data_axis)
                            if k == "batch_stats"
                            else v
                        )
                        for k, v in new_vars.items()
                    }
                updates, new_opt_state = opt.update(
                    grads, opt_state, params
                )
                new_params = optax.apply_updates(params, updates)
                # gate: a fully-padded batch must be a strict no-op. Uses
                # the data-axis-psum'd weight total (sums were psum'd
                # above) so every data shard takes the SAME branch — a
                # shard whose slice happens to be all padding must still
                # apply the collective update or shards silently diverge.
                valid = sums["w_sum"] > 0
                sel = lambda n, o: jax.tree.map(
                    lambda a, b: jnp.where(valid, a, b), n, o
                )
                new_variables = {**new_vars, "params": new_params}
                out_vars = sel(new_variables, variables)
                out_opt = sel(new_opt_state, opt_state)
                msums = {k: msums[k] + sums[k] for k in msums}
                return (out_vars, out_opt, msums), None

            (variables, opt_state, msums), _ = jax.lax.scan(
                step_body,
                (variables, opt_state, msums),
                jnp.arange(steps_per_epoch),
                unroll=min(cfg.scan_unroll, steps_per_epoch),
            )
            return (variables, opt_state, msums), None

        opt_state = opt.init(start_params)
        msums0 = zero_sums()
        ekeys = jax.vmap(lambda e: jax.random.fold_in(rng, e))(
            jnp.arange(cfg.epochs)
        )
        # A length-1 scan still emits a while loop with loop-carry layout
        # copies; inline tiny epoch counts instead. Bounded at 2 so the
        # program size cannot blow up as epochs x scan_unroll.
        if cfg.epochs <= 2:
            carry = (start_vars, opt_state, msums0)
            for e in range(cfg.epochs):
                carry, _ = epoch_body(carry, ekeys[e])
            variables, _, msums = carry
        else:
            (variables, _, msums), _ = jax.lax.scan(
                epoch_body, (start_vars, opt_state, msums0), ekeys
            )
        n_k = jnp.sum(mask_row)
        return variables, n_k, msums

    return local_update


def cohort_update_supported(model: FedModel, cfg: TrainConfig) -> bool:
    """Whether the cohort-grouped local update can replace
    ``vmap(local_update)`` exactly. Requires architecture support (see
    :meth:`FedModel.supports_cohort`) and a client optimizer whose state
    leaves all carry the per-client leading axis (sgd/momentum; adam's
    scalar step count cannot be gated per client in stacked form).
    Gradient clipping is excluded: ``optax.clip_by_global_norm`` over the
    stacked tree would compute one cohort-joint norm, not per-client
    norms."""
    return (
        model.supports_cohort()
        and cfg.optimizer == "sgd"
        and cfg.clip_norm == 0
    )


def build_cohort_local_update(
    model: FedModel,
    task: Task,
    cfg: TrainConfig,
    batch_size: int,
    max_n: int,
    cohort: int,
):
    """Cohort-major local update: the whole sampled cohort trains inside
    ONE network application per step (:mod:`fedml_tpu.models.cohort`),
    instead of ``vmap`` of the per-client update.

    Same contract as ``vmap(build_local_update(...), in_axes=(None, 0, 0,
    None, None, 0))`` — takes (global_vars, idx_rows [C, max_n], mask_rows,
    x, y, rngs [C]), returns (stacked_vars, n_k [C], metric sums with [C]
    leaves) — and the same numerics: per-client batch order, gradients,
    masking, and BN statistics agree to f32 round-off (the grouped network
    is the per-client network re-laid-out; reductions reassociate, so
    equality is not bitwise — see tests/test_cohort_conv.py's chaos
    calibration). It exists purely because XLA lowers
    one wide grouped conv far better than a batched-kernel conv on TPU
    (measured ~3x on the ResNet-56 round; see
    :mod:`fedml_tpu.ops.cohort_conv` for numbers).

    Per-client losses are summed, so ``d(total)/d(params_c)`` is exactly
    client c's gradient. A fully-padded batch contributes zero gradient
    AND is where-gated per client (params, optimizer state, and
    batch_stats all carry the leading [C] axis outside the network), so
    padded steps remain strict no-ops, matching the vmapped path.
    """
    assert max_n % batch_size == 0, (max_n, batch_size)
    steps_per_epoch = max_n // batch_size
    C = cohort
    opt = make_client_optimizer(cfg)
    compute_dtype = jnp.dtype(cfg.compute_dtype)
    mixed = compute_dtype != jnp.float32

    _to_compute = lambda t: _tree_to_dtype(t, compute_dtype)
    _to_f32 = lambda t: _tree_floats_back(t, compute_dtype)

    def loss_fn(stacked_params, static_stacked, x_cb, y_cb, w_cb, rng,
                global_params):
        if mixed:
            variables = {
                **_static_vars_to_dtype(static_stacked, compute_dtype),
                "params": _to_compute(stacked_params),
            }
            x_cb = _to_compute(x_cb)
        else:
            variables = {**static_stacked, "params": stacked_params}
        logits, new_vars = model.apply_cohort_train(variables, x_cb, rng)
        if mixed:
            logits = logits.astype(jnp.float32)
            new_vars = _to_f32(new_vars)
        sums = jax.vmap(task.metric_sums)(logits, y_cb, w_cb)  # [C] leaves
        loss = jnp.sum(
            sums["loss_sum"] / jnp.maximum(sums["w_sum"], 1.0)
        )
        if cfg.prox_mu > 0:
            diff = jax.tree.map(
                lambda p, g: p - g[None], stacked_params, global_params
            )
            loss = loss + 0.5 * cfg.prox_mu * T.tree_dot(diff, diff)
        return loss, (new_vars, sums)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def cohort_update(global_vars, idx_rows, mask_rows, x, y, rngs):
        global_params = global_vars["params"]
        stacked0 = jax.tree.map(
            lambda v: jnp.broadcast_to(v[None], (C,) + v.shape), global_vars
        )

        def epoch_body(carry, ekeys):
            variables, opt_state, msums = carry

            perms = jax.vmap(lambda k, m: _padded_perm(k, m, max_n))(
                ekeys, mask_rows
            )  # [C, max_n]

            def step_body(carry2, step):
                variables, opt_state, msums = carry2
                take = jax.lax.dynamic_slice_in_dim(
                    perms, step * batch_size, batch_size, axis=1
                )
                b_idx = jnp.take_along_axis(idx_rows, take, axis=1)
                w_b = jnp.take_along_axis(mask_rows, take, axis=1)
                x_b = jnp.take(x, b_idx, axis=0)
                y_b = jnp.take(y, b_idx, axis=0)
                # ONE key for the whole cohort, derived from client 0's
                # epoch key — safe only because cohort eligibility
                # (FedModel.supports_cohort) excludes stochastic layers:
                # apply_cohort_train never consumes this rng. A future
                # cohort-eligible model that does would need per-client
                # keys (vmap fold_in over ekeys) threaded into the fat
                # module instead.
                skey = jax.random.fold_in(ekeys[0], step)
                params = variables["params"]
                static_vars = {
                    k: v for k, v in variables.items() if k != "params"
                }
                (_, (new_vars, sums)), grads = grad_fn(
                    params, static_vars, x_b, y_b, w_b, skey, global_params
                )
                updates, new_opt_state = opt.update(grads, opt_state, params)
                new_params = optax.apply_updates(params, updates)
                valid = sums["w_sum"] > 0  # [C]
                sel = lambda n, o: jax.tree.map(
                    lambda a, b: jnp.where(
                        valid.reshape((C,) + (1,) * (a.ndim - 1)), a, b
                    ),
                    n,
                    o,
                )
                new_variables = {**new_vars, "params": new_params}
                out_vars = sel(new_variables, variables)
                out_opt = sel(new_opt_state, opt_state)
                msums = {k: msums[k] + sums[k] for k in msums}
                return (out_vars, out_opt, msums), None

            # Dynamic trip count: padded trailing steps are exact no-ops
            # (zero grads + where-gating), so running only
            # ceil(max cohort n_k / B) steps is bitwise-identical and
            # skips the padding waste entirely — the worst client in the
            # POPULATION no longer taxes every round, only the worst in
            # the sampled cohort. With hetero-LDA partitions this is the
            # single largest round-time lever (population max can be many
            # times the cohort max at 1000-client scale).
            def fori_body(step, carry2):
                carry2, _ = step_body(carry2, step)
                return carry2

            variables, opt_state, msums = jax.lax.fori_loop(
                0, cohort_steps, fori_body, (variables, opt_state, msums)
            )
            return (variables, opt_state, msums), None

        cohort_steps = jnp.minimum(
            jnp.ceil(jnp.max(jnp.sum(mask_rows, axis=1)) / batch_size)
            .astype(jnp.int32),
            steps_per_epoch,
        )
        opt_state = jax.vmap(opt.init)(stacked0["params"])
        msums0 = jax.tree.map(
            lambda s: jnp.zeros((C,), s.dtype), zero_sums()
        )
        # per-client epoch keys, identical to the vmapped path's
        # fold_in(rng_c, e) derivation so trajectories match exactly
        ekeys = jax.vmap(
            lambda r: jax.vmap(
                lambda e: jax.random.fold_in(r, e)
            )(jnp.arange(cfg.epochs))
        )(rngs)  # [C, epochs]
        if cfg.epochs <= 2:
            carry = (stacked0, opt_state, msums0)
            for e in range(cfg.epochs):
                carry, _ = epoch_body(carry, ekeys[:, e])
            variables, _, msums = carry
        else:
            (variables, _, msums), _ = jax.lax.scan(
                epoch_body,
                (stacked0, opt_state, msums0),
                jnp.moveaxis(ekeys, 1, 0),
            )
        n_k = jnp.sum(mask_rows, axis=1)
        return variables, n_k, msums

    return cohort_update


# ---------------------------------------------------------------------------
# Evaluation
# ---------------------------------------------------------------------------


def build_evaluator(model: FedModel, task: Task, eval_batch: int = 256):
    """Jitted global-test evaluation: pad to a multiple of ``eval_batch``,
    scan batches, reduce metric sums (reference
    ``_local_test_on_all_clients`` / ``test_on_server_for_all_clients``,
    ``FedAVGAggregator.py:110-164``)."""

    def evaluate(variables, x, y):
        n = x.shape[0]
        pad = (-n) % eval_batch
        xp = jnp.concatenate([x, jnp.zeros((pad,) + x.shape[1:], x.dtype)])
        yp = jnp.concatenate([y, jnp.zeros((pad,) + y.shape[1:], y.dtype)])
        w = jnp.concatenate([jnp.ones((n,)), jnp.zeros((pad,))])
        nb = (n + pad) // eval_batch

        def body(sums, i):
            sl = lambda a: jax.lax.dynamic_slice_in_dim(
                a, i * eval_batch, eval_batch
            )
            logits = model.apply_eval(variables, sl(xp))
            s = task.metric_sums(logits, sl(yp), sl(w))
            return {k: sums[k] + s[k] for k in sums}, None

        sums, _ = jax.lax.scan(body, zero_sums(), jnp.arange(nb))
        return {**finalize_sums(sums), "count": sums["count"]}

    return jax.jit(evaluate)

"""Secure aggregation: finite-field MPC primitives + TurboAggregate.

Re-design of the reference's TurboAggregate stack
(``fedml_api/distributed/turboaggregate/mpc_function.py``): BGW (Shamir)
secret sharing (``:62-108``), Lagrange Coded Computing encode/decode
(``:111-215``, ``LCC_encoding_with_points:228-262``), additive secret
sharing (``Gen_Additive_SS:218-226``), and modular-inverse Lagrange
coefficients (``gen_Lagrange_coeffs:38-58``).

Implementation notes (vs the reference's per-element python loops):
- All coefficient generation and share evaluation is VECTORIZED numpy
  int64 over a prime field with ``p < 2^31`` (default Mersenne prime
  2^31 - 1) so every intermediate product fits int64 exactly.
- Modular inverse via Fermat (``a^(p-2) mod p``) with exponentiation by
  squaring — no per-scalar extended-Euclid loop.
- The field layer stays on host: secure aggregation is a control-plane
  protocol over quantized updates (small integers); the TPU hot path
  (training) hands off a flat update vector, and the recovered SUM is
  exact, so secure FedAvg == plain FedAvg bit-for-bit after dequantize.
"""

from __future__ import annotations

import dataclasses

import numpy as np

P_DEFAULT = np.int64(2**31 - 1)  # Mersenne prime; products fit in int64


def _mod(a, p):
    return np.mod(a, p).astype(np.int64)


def mod_pow(base, exp: int, p) -> np.ndarray:
    """Vectorized modular exponentiation (square-and-multiply)."""
    base = _mod(np.asarray(base, np.int64), p)
    result = np.ones_like(base)
    e = int(exp)
    while e > 0:
        if e & 1:
            result = _mod(result * base, p)
        base = _mod(base * base, p)
        e >>= 1
    return result


def mod_inv(a, p) -> np.ndarray:
    """Fermat inverse a^(p-2) mod p (reference ``modular_inv``,
    ``mpc_function.py:4-18``, extended Euclid — same result, vectorized)."""
    return mod_pow(a, int(p) - 2, p)


def mod_matmul(a, b, p) -> np.ndarray:
    """Overflow-safe A @ B mod p: each rank-1 product is < p^2 < 2^62, and
    the accumulator is reduced after every addition, so no intermediate
    exceeds 2^63 (a plain int64 ``@`` would silently wrap for inner
    dimensions > 1)."""
    a = _mod(np.asarray(a, np.int64), p)
    b = _mod(np.asarray(b, np.int64), p)
    out = np.zeros((a.shape[0],) + b.shape[1:], np.int64)
    for k in range(a.shape[1]):
        out = _mod(out + a[:, k][(...,) + (None,) * (b.ndim - 1)] * b[k], p)
    return out


def gen_lagrange_coeffs(alpha_s, beta_s, p) -> np.ndarray:
    """U[i, j] = prod_{k != j} (alpha_i - beta_k) / (beta_j - beta_k) mod p
    (reference ``gen_Lagrange_coeffs``, ``mpc_function.py:38-58``),
    vectorized over both axes."""
    alpha_s = _mod(np.asarray(alpha_s, np.int64), p)
    beta_s = _mod(np.asarray(beta_s, np.int64), p)
    nb = len(beta_s)
    # den[j] = prod_{k != j} (beta_j - beta_k)
    diff_b = _mod(beta_s[:, None] - beta_s[None, :], p)  # [nb, nb]
    np.fill_diagonal(diff_b, 1)
    den = np.ones(nb, np.int64)
    for k in range(nb):
        den = _mod(den * diff_b[:, k], p)
    # num[i, j] = prod_{k != j} (alpha_i - beta_k)
    diff_a = _mod(alpha_s[:, None] - beta_s[None, :], p)  # [na, nb]
    prefix = np.ones_like(diff_a)
    suffix = np.ones_like(diff_a)
    for k in range(1, nb):
        prefix[:, k] = _mod(prefix[:, k - 1] * diff_a[:, k - 1], p)
    for k in range(nb - 2, -1, -1):
        suffix[:, k] = _mod(suffix[:, k + 1] * diff_a[:, k + 1], p)
    num = _mod(prefix * suffix, p)
    return _mod(num * mod_inv(den, p)[None, :], p)


# ---------------------------------------------------------------------------
# BGW (Shamir) secret sharing
# ---------------------------------------------------------------------------


def bgw_encode(x, n: int, t: int, p=P_DEFAULT, rng=None) -> np.ndarray:
    """Shamir shares of ``x`` [d]: share_i = sum_k r_k * alpha_i^k with
    r_0 = x (reference ``BGW_encoding``, ``mpc_function.py:62-75``).
    Returns [n, d]; any t+1 shares reconstruct, <=t reveal nothing."""
    rng = rng or np.random.default_rng()
    x = _mod(np.asarray(x, np.int64), p)
    d = x.shape[0]
    coeffs = rng.integers(0, int(p), size=(t + 1, d)).astype(np.int64)
    coeffs[0] = x
    alpha_s = _mod(np.arange(1, n + 1, dtype=np.int64), p)
    shares = np.zeros((n, d), np.int64)
    # Horner over the coefficient axis
    for k in range(t, -1, -1):
        shares = _mod(shares * alpha_s[:, None] + coeffs[k][None, :], p)
    return shares


def bgw_decode(shares, worker_idx, p=P_DEFAULT, t: int | None = None) -> np.ndarray:
    """Reconstruct the secret from >= t+1 shares via Lagrange at 0
    (reference ``BGW_decoding``, ``mpc_function.py:91-108``). Pass ``t`` to
    assert the share count meets the reconstruction threshold — with fewer
    than t+1 shares interpolation silently returns garbage."""
    worker_idx = np.asarray(worker_idx)
    if t is not None and len(worker_idx) < t + 1:
        raise ValueError(
            f"need >= {t + 1} shares to reconstruct, got {len(worker_idx)}"
        )
    alpha_s = _mod(worker_idx.astype(np.int64) + 1, p)
    lam = gen_lagrange_coeffs(np.zeros(1, np.int64), alpha_s, p)  # [1, R]
    return mod_matmul(lam, np.asarray(shares, np.int64), p)[0]


# ---------------------------------------------------------------------------
# Lagrange Coded Computing
# ---------------------------------------------------------------------------


def _lcc_points(n: int, k: int, t: int, p):
    n_beta = k + t
    stt_b = -(n_beta // 2)
    stt_a = -(n // 2)
    beta_s = _mod(np.arange(stt_b, stt_b + n_beta, dtype=np.int64), p)
    alpha_s = _mod(np.arange(stt_a, stt_a + n, dtype=np.int64), p)
    return alpha_s, beta_s


def lcc_encode(x, n: int, k: int, t: int, p=P_DEFAULT, rng=None):
    """LCC encoding (reference ``LCC_encoding``, ``mpc_function.py:111-133``):
    split x [m, d] into k chunks, pad with t random chunks, interpolate the
    degree-(k+t-1) polynomial through them at beta points, evaluate at the
    n alpha points. Returns [n, m//k, d]."""
    rng = rng or np.random.default_rng()
    x = _mod(np.asarray(x, np.int64), p)
    m, d = x.shape
    assert m % k == 0, (m, k)
    chunks = x.reshape(k, m // k, d)
    if t > 0:
        rand = rng.integers(0, int(p), size=(t, m // k, d)).astype(np.int64)
        chunks = np.concatenate([chunks, rand], axis=0)
    alpha_s, beta_s = _lcc_points(n, k, t, p)
    U = gen_lagrange_coeffs(alpha_s, beta_s, p)  # [n, k+t]
    flat = chunks.reshape(k + t, -1)
    enc = mod_matmul(U, flat, p)
    return enc.reshape(n, m // k, d)


def lcc_decode(f_eval, n: int, k: int, t: int, worker_idx, p=P_DEFAULT):
    """Decode chunk evaluations back to the k data chunks from a subset of
    workers (reference ``LCC_decoding``, ``mpc_function.py:195-215``)."""
    f_eval = _mod(np.asarray(f_eval, np.int64), p)
    if len(np.asarray(worker_idx)) < k + t:
        raise ValueError(
            f"LCC decode needs >= {k + t} evaluations, got"
            f" {len(np.asarray(worker_idx))}"
        )
    alpha_s, _ = _lcc_points(n, k, t, p)
    # decode targets the K data points only (reference n_beta = K)
    n_beta = k
    stt_b = -(n_beta // 2)
    beta_s = _mod(np.arange(stt_b, stt_b + n_beta, dtype=np.int64), p)
    alpha_eval = alpha_s[np.asarray(worker_idx)]
    U_dec = gen_lagrange_coeffs(beta_s, alpha_eval, p)  # [k, R]
    flat = f_eval.reshape(len(worker_idx), -1)
    out = mod_matmul(U_dec, flat, p)
    return out.reshape((k,) + f_eval.shape[1:])


def lcc_encode_with_points(x, alpha_s, beta_s, p=P_DEFAULT):
    """(reference ``LCC_encoding_with_points``, ``mpc_function.py:228-248``)"""
    U = gen_lagrange_coeffs(beta_s, alpha_s, p)
    return mod_matmul(U, np.asarray(x, np.int64), p)


def lcc_decode_with_points(f_eval, eval_points, target_points, p=P_DEFAULT):
    """(reference ``LCC_decoding_with_points``, ``mpc_function.py:251-262``)"""
    U_dec = gen_lagrange_coeffs(target_points, eval_points, p)
    return mod_matmul(U_dec, np.asarray(f_eval, np.int64), p)


def additive_shares(x, n: int, p=P_DEFAULT, rng=None) -> np.ndarray:
    """n shares summing to x mod p (reference ``Gen_Additive_SS``,
    ``mpc_function.py:218-226``)."""
    rng = rng or np.random.default_rng()
    x = _mod(np.asarray(x, np.int64), p)
    shares = rng.integers(0, int(p), size=(n - 1,) + x.shape).astype(np.int64)
    last = _mod(x - np.sum(_mod(shares, p), axis=0), p)
    return np.concatenate([shares, last[None]], axis=0)


# ---------------------------------------------------------------------------
# Fixed-point field quantization
# ---------------------------------------------------------------------------


def quantize(v: np.ndarray, scale_bits: int, p=P_DEFAULT) -> np.ndarray:
    """Float -> field: round(v * 2^q), negatives mapped to p + v (two's
    complement style centered lift; reference TA trainer
    ``transform_tensor_to_finite`` semantics)."""
    scaled = np.round(np.asarray(v, np.float64) * (1 << scale_bits))
    return _mod(scaled.astype(np.int64), p)


def dequantize(x: np.ndarray, scale_bits: int, p=P_DEFAULT) -> np.ndarray:
    """Field -> float with centered lift: values > p/2 are negative."""
    x = np.asarray(x, np.int64)
    centered = np.where(x > int(p) // 2, x - int(p), x)
    return centered.astype(np.float64) / (1 << scale_bits)


# ---------------------------------------------------------------------------
# TurboAggregate-style secure aggregation
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SecureAggregator:
    """Dropout-tolerant exact secure summation of client update vectors.

    Protocol (TurboAggregate, ``TA_Trainer.py`` / ``TA_decentralized_worker``):
    every client quantizes its update, splits it into additive shares (one
    per peer), and LCC/Shamir-encodes its share vector so the server can
    reconstruct the SUM from any ``t+1``-of-``n`` surviving clients while a
    coalition of <= ``t`` learns nothing about an individual update.

    In this in-process engine the share routing is a matrix transpose; over
    DCN it rides the transport layer. The recovered sum is EXACT (integer
    arithmetic), so secure-agg FedAvg equals plain FedAvg up to
    quantization (2^-scale_bits).
    """

    num_clients: int
    threshold: int  # max colluding / minimum surviving redundancy t
    scale_bits: int = 16
    p: np.int64 = P_DEFAULT
    seed: int = 0

    def __post_init__(self):
        # ONE generator for the aggregator's lifetime: re-seeding per call
        # would repeat the Shamir masking polynomials across rounds, letting
        # a single share-holder difference two rounds' shares and recover a
        # client's update delta.
        self._rng = np.random.default_rng(self.seed)

    def aggregate(
        self, updates: np.ndarray, dropped: list[int] | None = None
    ) -> np.ndarray:
        """``updates``: [n, d] float client vectors. Returns their exact sum
        (float), reconstructable as long as the surviving set has at least
        ``threshold + 1`` clients."""
        n, d = updates.shape
        assert n == self.num_clients
        dropped = set(dropped or [])
        survivors = [i for i in range(n) if i not in dropped]
        if len(survivors) < self.threshold + 1:
            raise ValueError(
                f"need >= {self.threshold + 1} survivors, have"
                f" {len(survivors)}"
            )
        rng = self._rng

        # 0. runtime envelope guard: the field must hold the SUM of n
        #    quantized updates with the centered lift, i.e.
        #    n * (max|v| * 2^q + 1/2) < p / 2 (the +1/2 per element is
        #    round()'s worst case). A larger delta would silently wrap
        #    mod p and dequantize to garbage — fail loudly instead.
        max_abs = float(np.max(np.abs(updates))) if updates.size else 0.0
        bound = (int(self.p) / 2.0 - n / 2.0) / (
            n * (1 << self.scale_bits)
        )
        if max_abs >= bound:
            raise ValueError(
                f"secure-aggregation overflow: max|update| = {max_abs:.4g}"
                f" >= field envelope {bound:.4g} "
                f"(p={self.p}, scale_bits={self.scale_bits}, n={n}); "
                "lower scale_bits, clip the updates, or use a larger "
                "prime"
            )

        # 1. quantize
        q = np.stack([quantize(updates[i], self.scale_bits, self.p)
                      for i in range(n)])

        # 2. each client Shamir-shares its vector to all peers
        #    shares[i, j] = share of client i's vector held by client j
        shares = np.stack([
            bgw_encode(q[i], n, self.threshold, self.p, rng)
            for i in range(n)
        ])  # [n, n, d]

        # 3. surviving clients locally sum the shares they hold — the sum
        #    of shares IS a share of the sum (linearity)
        held = [
            _mod(np.sum(shares[:, j, :], axis=0), self.p) for j in survivors
        ]

        # 4. server reconstructs the sum from the survivors' aggregate
        #    shares
        total_field = bgw_decode(
            np.stack(held), np.asarray(survivors), self.p, t=self.threshold
        )
        return dequantize(total_field, self.scale_bits, self.p)

    def aggregate_mean(
        self, updates: np.ndarray, dropped: list[int] | None = None
    ) -> np.ndarray:
        """Mean over ALL clients: ``dropped`` models clients that fail
        AFTER the sharing phase (the dropout the protocol tolerates), so
        every update still contributes to the reconstructed sum."""
        return self.aggregate(updates, dropped) / self.num_clients


class SecureFedAvgSim:
    """End-to-end TurboAggregate FedAvg: the compiled local updates of
    :class:`~fedml_tpu.algorithms.fedavg.FedAvgSim` composed with
    :class:`SecureAggregator` as the server's aggregation rule
    (reference ``distributed/turboaggregate/TA_Trainer.py`` — secure
    summation of client updates between local training and the model
    step).

    The TPU/host split follows the protocol's nature: local training and
    cohort sampling stay one compiled program; the sampled clients'
    weighted variable-deltas cross to the host ONCE per round as a flat
    [cohort, d] matrix, are secure-summed in the finite field, and the
    dequantized sum updates the global variables. ``run_round(state,
    dropped=[...])`` models clients failing after the sharing phase —
    their updates still reach the reconstructed sum, which is the
    dropout-tolerance the protocol provides.

    Equality: secure FedAvg == plain FedAvg up to quantization
    (2^-scale_bits per coordinate), pinned by
    ``tests/test_mpc.py::test_secure_fedavg_matches_plain``.
    Server optimizer semantics follow plain FedAvg (apply the weighted
    mean delta); fancy server optimizers are out of the protocol's scope.
    """

    def __init__(self, model, data, cfg, threshold: int | None = None,
                 scale_bits: int = 16):
        import jax

        from fedml_tpu.algorithms.fedavg import FedAvgSim

        # the secure sum replaces server_update entirely: the protocol
        # produces ONLY the weighted-mean delta, so server optimizers,
        # momentum, and robustness preprocessing (which need per-client
        # or reshaped aggregates) cannot apply. Refuse configs that ask
        # for them rather than silently dropping the semantics.
        f, t = cfg.fed, cfg.train
        unsupported = {
            "server_optimizer != 'sgd'": f.server_optimizer != "sgd",
            "server_lr != 1.0": f.server_lr != 1.0,
            "server_momentum": f.server_momentum != 0,
            "gmf": f.gmf != 0,
            "robust_method": f.robust_method not in (None, "", "mean"),
            "robust_norm_clip": f.robust_norm_clip > 0,
            "robust_noise_stddev": f.robust_noise_stddev > 0,
            "fednova": f.algorithm == "fednova",
            # the masked-sum protocol ravels the FULL variables tree;
            # the PEFT partition's pruned stacked updates would
            # misalign with it (fedml_tpu.peft) — refuse, don't drift
            "peft": getattr(f, "peft", "none") not in (None, "",
                                                       "none"),
        }
        bad = [k for k, v in unsupported.items() if v]
        if bad:
            raise ValueError(
                "secure aggregation (turboaggregate) computes a plain "
                "weighted-mean update; unsupported settings: "
                + ", ".join(bad)
            )
        self.inner = FedAvgSim(model, data, cfg)
        cohort = min(cfg.fed.clients_per_round, cfg.data.num_clients)
        self.secure = SecureAggregator(
            num_clients=cohort,
            threshold=cohort // 2 if threshold is None else threshold,
            scale_bits=scale_bits,
            seed=cfg.seed,
        )
        # the sampling/local-update prefix is FedAvgSim's own _locals —
        # alternate aggregation rules must not re-implement it
        self._locals_fn = jax.jit(
            lambda state, arrays: self.inner._locals(state, arrays)[:3]
        )

    def init(self):
        return self.inner.init()

    def run_round(self, state, round_idx=None, *,
                  dropped: list[int] | None = None):
        # round_idx is accepted (and ignored — the round counter lives in
        # the state) for the experiment harness's run_round(state, r)
        # protocol; ``dropped`` is keyword-only so the two can't collide
        import jax
        import jax.numpy as jnp
        from jax.flatten_util import ravel_pytree

        stacked_vars, n_k, msums = self._locals_fn(
            state, self.inner.arrays
        )
        n_k = np.asarray(jax.device_get(n_k), np.float64)
        msums = jax.device_get(msums)
        flat_global, unravel = ravel_pytree(state.variables)
        flat_global = np.asarray(jax.device_get(flat_global), np.float64)
        # [cohort, d] in ravel_pytree leaf order, STREAMED leaf-by-leaf
        # into a preallocated host matrix: at ResNet/transformer scale a
        # whole-tree device_get + concatenate would hold ~3 copies of the
        # cohort's parameters on the host at peak; this holds ~1 + one
        # leaf
        cohort = int(n_k.shape[0])
        flat_stacked = np.empty(
            (cohort, flat_global.shape[0]), np.float64
        )
        # ONE batched device_get for all leaves (a fetch costs ~110 ms
        # on the tunnelled backend — per-leaf gets would pay it ~60x),
        # then copy leaf-wise into the preallocated matrix so peak host
        # memory stays ~1 matrix + the fetched leaves
        host_leaves = jax.device_get(jax.tree.leaves(stacked_vars))
        off = 0
        for leaf in host_leaves:
            width = int(np.prod(leaf.shape[1:]))
            flat_stacked[:, off:off + width] = np.asarray(
                leaf, np.float64
            ).reshape(cohort, width)
            off += width
        # weight by n_k / sum(n_k) BEFORE quantizing: the secure sum then
        # directly yields the weighted mean, and the field never sees
        # n_k-scaled magnitudes — the quantization envelope
        # (|sum| < p / 2^(scale_bits+1)) holds whenever the deltas
        # themselves fit, independent of cohort size or client weights
        weights = n_k / max(float(n_k.sum()), 1.0)
        updates = (flat_stacked - flat_global) * weights[:, None]
        avg = self.secure.aggregate(updates, dropped)
        new_vars = unravel(jnp.asarray(flat_global + avg, jnp.float32))
        from fedml_tpu.algorithms.base import finalize_sums

        fin = finalize_sums(
            {k: np.sum(v) for k, v in msums.items()}
        )
        new_state = state._replace(
            variables=new_vars, round=state.round + 1
        )
        return new_state, {
            "train_loss": float(fin["loss"]),
            "train_acc": float(fin["acc"]),
        }

    def evaluate_global(self, state) -> dict:
        return self.inner.evaluate_global(state)

"""Heterogeneous-model clients: the fork's ``[(model, freq)]`` config,
bucketed for compilation.

The fork assigns each client its own architecture from a JSON config
(``experiment_client_configs/*.json``, parsed at
``fedml_experiments/standalone/utils/model.py:64-83`` and consumed by
``HeterogeneousModelBaseTrainerAPI.py:14``). Different architectures cannot
share one vmap, so the TPU engine buckets clients by architecture: one
stacked pytree + one compiled program per distinct model, a python loop
across buckets (configs cap distinct models at ~4), and cross-bucket
aggregation of the SHARED object (the generator for FedGDKD, the logit
tensor for FedMD) in plain array code.

Cohort sampling happens host-side with the reference's seeding
(``np.random.seed(round_idx)``, ``HeterogeneousModelBaseTrainerAPI.py:47-57``)
because bucket membership must be static per compiled call; each bucket's
cohort slice is padded to the bucket's max cohort size with a validity mask.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms import gan_core as G
from fedml_tpu.algorithms.base import build_evaluator, make_task
from fedml_tpu.algorithms.stack_utils import vmap_init
from fedml_tpu.config import ExperimentConfig, ModelConfig
from fedml_tpu.core import tree as T
from fedml_tpu.data.federated import FederatedArrays, FederatedData, arrays_and_batch
from fedml_tpu.models import create_model
from fedml_tpu.models.base import FedModel
from fedml_tpu.models.gan import GanModel

Pytree = Any


@dataclasses.dataclass(frozen=True)
class ClientModelSpec:
    """One entry of the fork's ``client_models`` config."""

    model: ModelConfig
    freq: int


def parse_client_config(
    config: str | dict, num_classes: int, input_shape: tuple[int, ...]
) -> list[ClientModelSpec]:
    """Parse the fork's JSON client-model config
    (``experiment_client_configs/*.json``: entries with ``model``, ``freq``,
    optional ``layers`` for cnn_custom)."""
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    specs = []
    for entry in config["client_models"]:
        name = entry["model"]
        extra = []
        if name == "cnn_custom":
            # the fork's parameterised CNN: conv widths from the config
            # (model/cv/cnn_custom.py:8)
            extra = [("convs", tuple(entry["layers"]))]
        specs.append(
            ClientModelSpec(
                model=ModelConfig(
                    name=name,
                    num_classes=num_classes,
                    input_shape=tuple(input_shape),
                    extra=tuple(extra),
                ),
                freq=int(entry["freq"]),
            )
        )
    return specs


@dataclasses.dataclass
class Bucket:
    """Clients sharing one architecture."""

    model: FedModel
    client_ids: np.ndarray  # global client indices in this bucket
    stack: Pytree = None  # [len(client_ids), ...] variables
    # members' position-in-bucket keyed by global client id
    pos: dict | None = None

    def __post_init__(self):
        self.pos = {int(c): i for i, c in enumerate(self.client_ids)}


def build_buckets(
    specs: Sequence[ClientModelSpec], root_key, num_clients: int
) -> list[Bucket]:
    """Assign client ids to architectures in config order (the fork
    instantiates ``freq`` clients per entry sequentially,
    ``fedgdkd/server.py:55-64``) and merge entries with identical model
    configs into one bucket."""
    assert sum(s.freq for s in specs) == num_clients, (
        "client_models freqs must sum to num_clients"
    )
    by_cfg: dict[ModelConfig, list[int]] = {}
    cid = 0
    for s in specs:
        ids = by_cfg.setdefault(s.model, [])
        ids.extend(range(cid, cid + s.freq))
        cid += s.freq
    buckets = []
    for b_idx, (mcfg, ids) in enumerate(by_cfg.items()):
        model = create_model(mcfg)
        stack = vmap_init(
            model.init, jax.random.fold_in(root_key, 0xB0 + b_idx), len(ids)
        )
        buckets.append(
            Bucket(model=model, client_ids=np.asarray(ids), stack=stack)
        )
    return buckets


def sample_cohort(
    round_idx: int, num_clients: int, clients_per_round: int
) -> np.ndarray:
    """Reference-faithful seeded sampling
    (``HeterogeneousModelBaseTrainerAPI._client_sampling``: seed with the
    round index, choice without replacement)."""
    if clients_per_round >= num_clients:
        return np.arange(num_clients)
    rng = np.random.default_rng(round_idx)
    return np.sort(rng.choice(num_clients, clients_per_round, replace=False))


def bucket_cohorts(
    buckets: Sequence[Bucket], cohort: np.ndarray, pad_to: int
) -> list[tuple[np.ndarray, np.ndarray]]:
    """Split a cohort by bucket; returns per-bucket (padded member
    positions [pad_to], validity mask [pad_to])."""
    out = []
    cohort_set = set(int(c) for c in cohort)
    for b in buckets:
        members = [b.pos[c] for c in sorted(cohort_set) if c in b.pos]
        k = len(members)
        padded = np.zeros(pad_to, np.int32)
        padded[:k] = members
        valid = np.zeros(pad_to, np.float32)
        valid[:k] = 1.0
        out.append((padded, valid))
    return out


class HeteroFedGDKD:
    """FedGDKD with per-client heterogeneous classifiers — the fork's
    headline configuration (``fedgdkd/server.py:18-68`` builds clients from
    ``[(model, freq)]``). The generator is the only shared-architecture
    object; classifiers live in per-bucket stacks.

    Per round: host samples the cohort and splits it by bucket; each bucket
    runs its compiled ssgan local update; the generator is aggregated
    across ALL buckets weighted by n_k; the distillation set is generated
    once; per-bucket logit extraction concatenates into the cohort-wide
    ``[C, S, K]`` tensor for the leave-one-out teacher; per-bucket KD
    writes classifiers back.
    """

    def __init__(
        self,
        gen: GanModel,
        specs: Sequence[ClientModelSpec],
        data: FederatedData,
        cfg: ExperimentConfig,
    ):
        self.gen, self.cfg = gen, cfg
        self.task = make_task(data.task)
        self.arrays, self.batch_size = arrays_and_batch(data, cfg.data)
        self.max_n = self.arrays.max_client_samples
        self.root_key = jax.random.key(cfg.seed)
        self.buckets = build_buckets(
            specs, self.root_key, self.arrays.num_clients
        )
        gan = cfg.gan
        self.synth_size = (
            gan.distillation_size // self.batch_size
        ) * self.batch_size or self.batch_size
        self.generate = jax.jit(
            G.build_dataset_generator(gen, self.synth_size, self.batch_size)
        )
        self.pad_to = min(
            cfg.fed.clients_per_round, self.arrays.num_clients
        )
        # per-bucket compiled phases
        self._local, self._extract, self._kd, self._eval = [], [], [], []
        for b in self.buckets:
            disc = G.DiscHandle.from_fed_model(b.model)
            lu = G.build_gan_local_update(
                gen, disc, cfg.train, gan, self.batch_size, self.max_n,
                mode="ssgan",
            )
            self._local.append(
                jax.jit(
                    jax.vmap(lu, in_axes=(None, 0, 0, 0, None, None, 0))
                )
            )
            ex = G.build_logit_extractor(
                disc, self.synth_size, self.batch_size
            )
            self._extract.append(jax.jit(jax.vmap(ex, in_axes=(0, None))))
            kd = G.build_kd_update(
                disc, cfg.train, gan, self.synth_size, self.batch_size
            )
            self._kd.append(
                jax.jit(jax.vmap(kd, in_axes=(0, None, None, 0, 0)))
            )
            self._eval.append(build_evaluator(b.model, self.task))
        self.gen_vars = self.gen.init(
            jax.random.fold_in(self.root_key, 0x6E4)
        )
        self.round = 0
        # drift-correction state (reference fedgdkd/server.py:92-97): last
        # round's distillation set + cohort-mean teacher + membership
        self._prev_synth: tuple | None = None
        self._prev_teacher: np.ndarray | None = None
        self._prev_sampled: set[int] = set()

    def run_round(self) -> dict:
        cfg = self.cfg.fed
        arrays = self.arrays
        cohort = sample_cohort(
            self.round, arrays.num_clients, cfg.clients_per_round
        )
        rkey = jax.random.fold_in(self.root_key, self.round)
        per_bucket = bucket_cohorts(self.buckets, cohort, self.pad_to)

        # --- drift correction for new joiners (server.py:92-97): KD
        #     against last round's distillation set + mean teacher ---
        if self._prev_teacher is not None:
            px, py = self._prev_synth
            teacher_full = jnp.broadcast_to(
                jnp.asarray(self._prev_teacher)[None],
                (self.pad_to,) + self._prev_teacher.shape,
            )
            for bi, (b, (members, valid)) in enumerate(
                zip(self.buckets, per_bucket)
            ):
                gids = b.client_ids[members]
                is_new = np.array(
                    [
                        v > 0 and int(g) not in self._prev_sampled
                        for g, v in zip(gids, valid)
                    ]
                )
                if not is_new.any():
                    continue
                cls_vars = jax.tree.map(lambda s: s[members], b.stack)
                ckeys = jax.vmap(
                    lambda c: jax.random.fold_in(
                        jax.random.fold_in(rkey, 0xD1F7), c
                    )
                )(jnp.asarray(gids))
                corrected, _ = self._kd[bi](
                    cls_vars, px, py, teacher_full, ckeys
                )
                upd = members[is_new]
                b.stack = jax.tree.map(
                    lambda s, n: s.at[jnp.asarray(upd)].set(
                        n[jnp.asarray(is_new)]
                    ),
                    b.stack, corrected,
                )

        # --- GAN phase per bucket ---
        # Everything stays ON DEVICE across buckets: n_total is a device
        # scalar, generator aggregation is device tree math, and the
        # cohort-wide logit tensor below is a device concatenate — the only
        # host work per round is bucket bookkeeping over (host) cohort
        # metadata, so there is no device->host sync in the hot loop.
        gen_sums = None
        n_total = None
        new_cls = []
        for bi, (b, (members, valid)) in enumerate(
            zip(self.buckets, per_bucket)
        ):
            if valid.sum() == 0:
                new_cls.append(None)
                continue
            gids = b.client_ids[members]  # global client ids (padded)
            ckeys = jax.vmap(
                lambda c: jax.random.fold_in(rkey, c)
            )(jnp.asarray(gids))
            cls_vars = jax.tree.map(lambda s: s[members], b.stack)
            g_stack, cls_vars, n_k, _ = self._local[bi](
                self.gen_vars, cls_vars, arrays.idx[gids],
                arrays.mask[gids], arrays.x, arrays.y, ckeys,
            )
            n_k = n_k * jnp.asarray(valid, n_k.dtype)  # pad rows weightless
            wsum = T.tree_weighted_sum(g_stack, n_k)
            gen_sums = (
                wsum if gen_sums is None else T.tree_add(gen_sums, wsum)
            )
            bsum = jnp.sum(n_k)
            n_total = bsum if n_total is None else n_total + bsum
            new_cls.append((members, valid, cls_vars, n_k))

        self.gen_vars = jax.tree.map(
            lambda s: s / jnp.maximum(n_total, 1.0), gen_sums
        )

        # --- distillation set from the aggregated generator ---
        synth_x, synth_y = self.generate(
            self.gen_vars, jax.random.fold_in(rkey, 0x5EED)
        )

        # --- cohort-wide logits -> leave-one-out teachers (device) ---
        logits_chunks = []
        for bi, entry in enumerate(new_cls):
            if entry is None:
                continue
            members, valid, cls_vars, _ = entry
            lg = self._extract[bi](cls_vars, synth_x)  # [pad_to, S, K]
            k = int(valid.sum())  # host metadata, not a device sync
            logits_chunks.append(lg[:k])
        logits = jnp.concatenate(logits_chunks, axis=0)  # [C, S, K]
        c = logits.shape[0]
        loo = (logits.sum(0)[None] - logits) / max(c - 1, 1)

        # --- per-bucket KD with its members' teachers ---
        offset = 0
        for bi, entry in enumerate(new_cls):
            if entry is None:
                continue
            members, valid, cls_vars, _ = entry
            k = int(valid.sum())
            teacher = jnp.zeros((self.pad_to,) + loo.shape[1:])
            teacher = teacher.at[:k].set(loo[offset:offset + k])
            offset += k
            gids = self.buckets[bi].client_ids[members]
            ckeys = jax.vmap(
                lambda cid: jax.random.fold_in(
                    jax.random.fold_in(rkey, 0xAD), cid
                )
            )(jnp.asarray(gids))
            cls_vars, _ = self._kd[bi](
                cls_vars, synth_x, synth_y, teacher, ckeys
            )
            # scatter only valid members back into the bucket stack
            b = self.buckets[bi]
            sel = valid > 0
            upd_members = members[sel]
            b.stack = jax.tree.map(
                lambda s, n: s.at[jnp.asarray(upd_members)].set(
                    n[jnp.asarray(sel)]
                ),
                b.stack,
                cls_vars,
            )

        # record drift-correction state for the next round (device arrays;
        # nothing is pulled to host)
        self._prev_synth = (synth_x, synth_y)
        self._prev_teacher = logits.mean(axis=0)  # [S, K] device
        self._prev_sampled = set(int(c) for c in cohort)

        self.round += 1
        return {"cohort": cohort.tolist(), "num_buckets": len(self.buckets)}

    def evaluate_clients(self) -> dict:
        accs = []
        for bi, b in enumerate(self.buckets):
            for i in range(len(b.client_ids)):
                v = jax.tree.map(lambda s: s[i], b.stack)
                m = self._eval[bi](
                    v, self.arrays.test_x, self.arrays.test_y
                )
                accs.append(float(m["acc"]))
        return {
            "test_acc": float(np.mean(accs)),
            "per_client_acc": accs,
        }

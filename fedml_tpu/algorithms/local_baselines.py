"""Non-federated baselines: local-only training and pooled centralized.

Reference equivalents:
- ``fedml_api/standalone/baseline/`` — every client trains ONLY on its own
  data, no communication; the lower bound for FL comparisons.
- ``fedml_api/standalone/centralised/`` + ``fedml_api/centralized/
  centralized_trainer.py:9`` — one model on the pooled dataset; the upper
  bound (and the convergence-equivalence oracle partner: full-batch FedAvg
  over all clients == centralized full-batch GD, ``CI-script-fedavg.sh:45-66``).

Both reuse the compiled ``build_local_update`` hot loop; centralized is
expressed as a single "client" owning every sample, which makes the oracle
comparison an exact code-path match.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.base import (
    build_evaluator,
    build_local_update,
    finalize_sums,
    make_task,
)
from fedml_tpu.algorithms.stack_utils import evaluate_stack, vmap_init
from fedml_tpu.config import ExperimentConfig
from fedml_tpu.data.federated import FederatedArrays, FederatedData, arrays_and_batch

Pytree = Any


class BaselineState(NamedTuple):
    model_stack: Pytree  # [N, ...] independent local models
    round: jax.Array


class BaselineSim:
    """Local-training-only baseline (reference ``standalone/baseline``)."""

    def __init__(self, model, data: FederatedData, cfg: ExperimentConfig):
        self.model, self.cfg = model, cfg
        self.task = make_task(data.task)
        self.arrays, self.batch_size = arrays_and_batch(data, cfg.data)
        max_n = self.arrays.max_client_samples
        self.local_update = build_local_update(
            model, self.task, cfg.train, self.batch_size, max_n
        )
        self.evaluator = build_evaluator(model, self.task)
        self.root_key = jax.random.key(cfg.seed)
        self._round_fn = jax.jit(self._round, donate_argnums=(0,))

    def init(self) -> BaselineState:
        return BaselineState(
            vmap_init(
                self.model.init,
                jax.random.fold_in(self.root_key, 0x7FFFFFFF),
                self.arrays.num_clients,
            ),
            jnp.asarray(0, jnp.int32),
        )

    def _round(self, state: BaselineState, arrays: FederatedArrays):
        n = arrays.num_clients
        rkey = jax.random.fold_in(self.root_key, state.round)
        ckeys = jax.vmap(lambda c: jax.random.fold_in(rkey, c))(
            jnp.arange(n)
        )
        stack, _, msums = jax.vmap(
            self.local_update, in_axes=(0, 0, 0, None, None, 0)
        )(state.model_stack, arrays.idx, arrays.mask, arrays.x, arrays.y,
          ckeys)
        fin = finalize_sums(jax.tree.map(jnp.sum, msums))
        return (
            BaselineState(stack, state.round + 1),
            {"train_loss": fin["loss"], "train_acc": fin["acc"]},
        )

    def run_round(self, state: BaselineState):
        return self._round_fn(state, self.arrays)

    def evaluate_clients(self, state: BaselineState) -> dict:
        return evaluate_stack(
            self.evaluator, state.model_stack, self.arrays.test_x,
            self.arrays.test_y, self.arrays.num_clients,
        )


def pooled_data(data: FederatedData) -> FederatedData:
    """Collapse a federated dataset into one pooled client (reference
    centralized collapse, ``standalone/utils/dataset.py:149-156``)."""
    all_train = np.concatenate(
        [data.train_idx_map[i] for i in range(data.num_clients)]
    )
    all_test = np.concatenate(
        [data.test_idx_map[i] for i in range(data.num_clients)]
    )
    return FederatedData(
        data.x_train, data.y_train, data.x_test, data.y_test,
        {0: all_train}, {0: all_test}, data.num_classes, data.task,
    )


class CentralizedTrainer:
    """Pooled-data trainer (reference ``centralized_trainer.py:9``): the
    compiled local-update over one all-owning client; one ``run_round`` =
    ``cfg.train.epochs`` epochs of minibatch SGD."""

    def __init__(self, model, data: FederatedData, cfg: ExperimentConfig):
        self.model, self.cfg = model, cfg
        pooled = pooled_data(data)
        self.task = make_task(pooled.task)
        self.arrays, self.batch_size = arrays_and_batch(pooled, cfg.data)
        max_n = self.arrays.max_client_samples
        self.local_update = build_local_update(
            model, self.task, cfg.train, self.batch_size, max_n
        )
        self.evaluator = build_evaluator(model, self.task)
        self.root_key = jax.random.key(cfg.seed)
        self._fit = jax.jit(
            lambda v, arrays, key: self.local_update(
                v, arrays.idx[0], arrays.mask[0], arrays.x, arrays.y, key
            )
        )

    def init(self) -> Pytree:
        return self.model.init(jax.random.fold_in(self.root_key, 0x7FFFFFFF))

    def run_round(self, variables: Pytree, round_idx: int):
        key = jax.random.fold_in(self.root_key, round_idx)
        variables, _, msums = self._fit(variables, self.arrays, key)
        fin = finalize_sums(jax.tree.map(jnp.sum, msums))
        return variables, {
            "train_loss": float(fin["loss"]),
            "train_acc": float(fin["acc"]),
        }

    def evaluate(self, variables: Pytree) -> dict:
        m = self.evaluator(variables, self.arrays.test_x, self.arrays.test_y)
        return {k: float(v) for k, v in m.items()}

    def evaluate_train(self, variables: Pytree) -> dict:
        m = self.evaluator(variables, self.arrays.x, self.arrays.y)
        return {k: float(v) for k, v in m.items()}

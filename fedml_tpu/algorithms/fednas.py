"""FedNAS: federated neural architecture search over the DARTS space.

Reference (``fedml_api/distributed/fednas/``): each client runs DARTS
bilevel steps locally — architecture (alpha) step on a held-out split, then
weight step on the train split (``model/cv/darts/architect.py:13``) — and
the server aggregates BOTH weights and alphas with sample-weighted FedAvg
(``FedNASAggregator.py:39-41``). Search is followed by a train phase on the
derived genotype (``run_fednas_search.sh`` / ``run_fednas_train.sh``);
derivation here is :func:`fedml_tpu.models.darts.derive_genotype`.

The architect uses the first-order DARTS approximation (reference
``--unrolled false`` default path): alpha gradient evaluated at the current
weights. One compiled program per round, cohort vmapped.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.algorithms.base import make_client_optimizer
from fedml_tpu.config import ExperimentConfig
from fedml_tpu.core import random as R
from fedml_tpu.core import tree as T
from fedml_tpu.data.federated import FederatedArrays, FederatedData, arrays_and_batch
from fedml_tpu.models.darts import DARTSNetwork

Pytree = Any


class FedNASState(NamedTuple):
    variables: Pytree  # params + batch_stats + arch collections
    round: jax.Array


class FedNASSim:
    """Compiled federated DARTS search."""

    def __init__(
        self,
        model: DARTSNetwork,
        data: FederatedData,
        cfg: ExperimentConfig,
        arch_lr: float = 3e-4,
    ):
        self.model = model
        self.cfg = cfg
        self.arrays, self._resolved_batch = arrays_and_batch(data, cfg.data)
        self.max_n = self.arrays.max_client_samples
        # the 50/50 train/val split for the architect needs at least one
        # batch per half — cap the batch size accordingly
        self.batch_size = max(1, min(self._resolved_batch, self.max_n // 2))
        self.input_shape = self.arrays.x.shape[1:]
        self.w_opt = make_client_optimizer(cfg.train)
        self.a_opt = optax.adam(arch_lr)  # reference arch_lr adam
        self.root_key = jax.random.key(cfg.seed)
        self.local_update = self._build_local_update()
        self._round_fn = jax.jit(self._round, donate_argnums=(0,))

    def _init_vars(self, rng):
        dummy = jnp.zeros((1,) + tuple(self.input_shape), jnp.float32)
        return self.model.init({"params": rng}, dummy, train=False)

    def _apply_train(self, variables, x):
        out, mut = self.model.apply(
            variables, x, train=True, mutable=["batch_stats"]
        )
        return out, {**variables, **mut}

    def _build_local_update(self):
        def loss_wrt(part, variables, xb, yb, wb):
            """CE loss as a function of one collection (params | arch)."""

            def f(leaf):
                v = {**variables, part: leaf}
                logits, new_vars = self._apply_train(v, xb)
                ce = optax.softmax_cross_entropy_with_integer_labels(
                    logits, yb
                )
                loss = jnp.sum(ce * wb) / jnp.maximum(jnp.sum(wb), 1.0)
                return loss, new_vars

            return f

        def update(variables, idx_row, mask_row, x, y, rng):
            # split the client's (padded) indices into train/val halves
            # (reference DARTS uses a 50/50 split of local data for the
            # architect, main_fednas local search setup)
            half = self.max_n // 2

            w_os = self.w_opt.init(variables["params"])
            a_os = self.a_opt.init(variables["arch"])

            def epoch_body(carry, ekey):
                variables, w_os, a_os = carry
                perm = jax.random.permutation(ekey, self.max_n)
                order = jnp.argsort(1.0 - mask_row[perm], stable=True)
                perm = perm[order]
                # interleave so both halves contain real samples
                train_half = perm[0::2]
                val_half = perm[1::2]
                n_steps = max(1, half // self.batch_size)

                def step(carry2, s):
                    variables, w_os, a_os = carry2

                    def batch(idx_src):
                        take = jax.lax.dynamic_slice_in_dim(
                            idx_src, s * self.batch_size, self.batch_size
                        )
                        b_idx = idx_row[take]
                        return (
                            jnp.take(x, b_idx, axis=0),
                            jnp.take(y, b_idx, axis=0),
                            mask_row[take],
                        )

                    # 1. architect step on the val half
                    #    (architect.py:13 step(), first-order)
                    xv, yv, wv = batch(val_half)
                    (a_loss, new_vars), a_grads = jax.value_and_grad(
                        loss_wrt("arch", variables, xv, yv, wv),
                        has_aux=True,
                    )(variables["arch"])
                    au, new_a_os = self.a_opt.update(
                        a_grads, a_os, variables["arch"]
                    )
                    new_arch = optax.apply_updates(variables["arch"], au)
                    variables2 = {**new_vars, "arch": new_arch}
                    valid_v = jnp.sum(wv) > 0
                    sel_v = lambda a, b: jax.tree.map(
                        lambda p, q: jnp.where(valid_v, p, q), a, b
                    )
                    variables2 = sel_v(variables2, variables)
                    a_os2 = sel_v(new_a_os, a_os)

                    # 2. weight step on the train half
                    xt, yt, wt = batch(train_half)
                    (w_loss, new_vars2), w_grads = jax.value_and_grad(
                        loss_wrt("params", variables2, xt, yt, wt),
                        has_aux=True,
                    )(variables2["params"])
                    wu, new_w_os = self.w_opt.update(
                        w_grads, w_os, variables2["params"]
                    )
                    new_params = optax.apply_updates(
                        variables2["params"], wu
                    )
                    variables3 = {**new_vars2, "params": new_params}
                    valid_t = jnp.sum(wt) > 0
                    sel_t = lambda a, b: jax.tree.map(
                        lambda p, q: jnp.where(valid_t, p, q), a, b
                    )
                    variables3 = sel_t(variables3, variables2)
                    w_os2 = sel_t(new_w_os, w_os)
                    return (variables3, w_os2, a_os2), None

                carry2, _ = jax.lax.scan(
                    step, (variables, w_os, a_os), jnp.arange(n_steps)
                )
                return carry2, None

            ekeys = jax.vmap(lambda e: jax.random.fold_in(rng, e))(
                jnp.arange(self.cfg.train.epochs)
            )
            (variables, _, _), _ = jax.lax.scan(
                epoch_body, (variables, w_os, a_os), ekeys
            )
            return variables, jnp.sum(mask_row)

        return update

    def init(self) -> FedNASState:
        return FedNASState(
            self._init_vars(jax.random.fold_in(self.root_key, 0x7FFFFFFF)),
            jnp.asarray(0, jnp.int32),
        )

    def _round(self, state: FedNASState, arrays: FederatedArrays):
        cfg = self.cfg.fed
        rkey = R.round_key(self.root_key, state.round)
        cohort = R.sample_clients(
            jax.random.fold_in(rkey, 0), arrays.num_clients,
            cfg.clients_per_round,
        )
        ckeys = jax.vmap(lambda c: R.client_key(rkey, c))(cohort)
        stacked, n_k = jax.vmap(
            self.local_update, in_axes=(None, 0, 0, None, None, 0)
        )(state.variables, arrays.idx[cohort], arrays.mask[cohort],
          arrays.x, arrays.y, ckeys)
        # aggregate weights AND alphas (FedNASAggregator.py:39-41)
        new_vars = T.tree_weighted_mean(stacked, n_k)
        return FedNASState(new_vars, state.round + 1), {}

    def run_round(self, state: FedNASState):
        return self._round_fn(state, self.arrays)

    def evaluate(self, state: FedNASState, eval_batch: int = 64) -> dict:
        """Batched jitted eval — the supernet materializes a
        [|ops|, B, H, W, C] stack per edge, so the whole test set in one
        apply would OOM at CIFAR scale."""
        x, y = self.arrays.test_x, self.arrays.test_y
        n = x.shape[0]
        pad = (-n) % eval_batch
        xp = jnp.concatenate([x, jnp.zeros((pad,) + x.shape[1:], x.dtype)])
        yp = jnp.concatenate([y, jnp.zeros((pad,), y.dtype)])
        w = jnp.concatenate([jnp.ones((n,)), jnp.zeros((pad,))])

        @jax.jit
        def run(variables):
            def body(acc, i):
                sl = lambda a: jax.lax.dynamic_slice_in_dim(
                    a, i * eval_batch, eval_batch
                )
                logits = self.model.apply(variables, sl(xp), train=False)
                hit = (jnp.argmax(logits, -1) == sl(yp)).astype(jnp.float32)
                return acc + jnp.sum(hit * sl(w)), None

            acc, _ = jax.lax.scan(
                body, jnp.asarray(0.0),
                jnp.arange((n + pad) // eval_batch),
            )
            return acc

        return {"test_acc": float(run(state.variables)) / max(n, 1)}

"""Split-compute FL: FedGKT, SplitNN, and classical vertical FL.

These are the reference's model-parallel-across-trust-boundary algorithms
(SURVEY.md §2.7): activations/logits/features cross the client-server
boundary instead of weights. In the compiled simulator the boundary is an
explicit array handoff between separately-optimized parameter groups — the
same cut where a multi-host deployment ships tensors over ICI/DCN via the
transport layer.

- **FedGKT** (``fedml_api/distributed/fedgkt/``): client trains a small
  edge model with ``CE + alpha*KL(client_logits, server_logits)``
  (``GKTClientTrainer.py:73-78``), uploads extracted feature maps +
  logits; the server trains a large model on the features with
  ``KL(server_out, client_logits) + alpha*CE``
  (``GKTServerTrainer.py:261-263`` — note the asymmetric weighting) and
  returns per-sample server logits for the next client round.
- **SplitNN** (``fedml_api/distributed/split_nn/``): clients own the lower
  layers, the server the upper; every batch crosses the boundary forward
  (activations) and backward (gradients) (``client.py:24-34``,
  ``server.py:40-57``); clients take turns in a ring.
- **Vertical FL** (``fedml_api/standalone/classical_vertical_fl/``):
  feature-partitioned parties; the guest holds labels, sums the parties'
  logit components, computes the BCE loss, and returns the common gradient
  (``vfl.py:21-75``, ``party_models.py``).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.algorithms import kd as KD
from fedml_tpu.algorithms.base import make_client_optimizer
from fedml_tpu.algorithms.stack_utils import stack_gather, vmap_init
from fedml_tpu.config import ExperimentConfig
from fedml_tpu.core import random as R
from fedml_tpu.data.federated import FederatedArrays, FederatedData, arrays_and_batch

Pytree = Any


# GKT's ``KL_Loss`` (``fedgkt/utils.py:75-95``) is the same T^2-scaled
# batchmean KL as the Hinton soft target; one implementation lives in
# fedml_tpu.algorithms.kd (the +1e-7 in the reference guards log(0);
# log-softmax there is exact).
kl_temperature = KD.soft_target


# ---------------------------------------------------------------------------
# FedGKT
# ---------------------------------------------------------------------------


class FedGKTState(NamedTuple):
    client_stack: Pytree  # [N, ...] edge models
    server_vars: Pytree
    server_opt_state: Any
    server_logits: jax.Array  # [N_total, K] teacher logits per train sample
    has_server_logits: jax.Array  # scalar bool
    round: jax.Array


class FedGKTSim:
    """Group Knowledge Transfer on one compiled graph per round.

    All clients participate each round (the reference is cross-silo:
    ``GKTServerTrainer`` keeps every client's features). Feature maps for
    the full train set are rematerialized per round from the current edge
    models instead of being stored host-side — on TPU the recompute is
    cheaper than the HBM for a stored ``[N, H, W, C]`` bank plus transfers.
    """

    def __init__(
        self,
        client_model,  # GKTClientResNet-like: (x) -> (features, logits)
        server_model,  # GKTServerResNet-like: (features) -> logits
        data: FederatedData,
        cfg: ExperimentConfig,
        temperature: float = 3.0,
        alpha: float = 1.0,
    ):
        self.client_model = client_model
        self.server_model = server_model
        self.cfg = cfg
        self.T = float(temperature)
        self.alpha = float(alpha)
        self.arrays, self.batch_size = arrays_and_batch(data, cfg.data)
        self.max_n = self.arrays.max_client_samples
        self.num_classes = self.arrays.num_classes
        self.input_shape = self.arrays.x.shape[1:]
        self.n_total = self.arrays.x.shape[0]
        self.c_opt = make_client_optimizer(cfg.train)
        self.s_opt = make_client_optimizer(cfg.train)
        self.root_key = jax.random.key(cfg.seed)
        self._round_fn = jax.jit(self._round, donate_argnums=(0,))

    # -- model plumbing -----------------------------------------------------
    def _client_init(self, rng):
        dummy = jnp.zeros((1,) + tuple(self.input_shape), jnp.float32)
        return self.client_model.init({"params": rng}, dummy, train=False)

    def _client_apply_train(self, variables, x):
        (features, logits), mut = self.client_model.apply(
            variables, x, train=True, mutable=["batch_stats"]
        )
        return features, logits, {**variables, **mut}

    def _client_apply_eval(self, variables, x):
        return self.client_model.apply(variables, x, train=False)

    def _server_init(self, rng, feat_shape):
        dummy = jnp.zeros((1,) + tuple(feat_shape), jnp.float32)
        return self.server_model.init({"params": rng}, dummy, train=False)

    def _server_apply_train(self, variables, f):
        logits, mut = self.server_model.apply(
            variables, f, train=True, mutable=["batch_stats"]
        )
        return logits, {**variables, **mut}

    def _server_apply_eval(self, variables, f):
        return self.server_model.apply(variables, f, train=False)

    # -- phases -------------------------------------------------------------
    def _client_phase(self, c_vars, idx_row, mask_row, x, y, s_logits,
                      use_kd, rng):
        """Edge training: CE + alpha*KL to the server's per-sample logits
        (``GKTClientTrainer.py:66-90``)."""
        steps = self.max_n // self.batch_size

        def loss_fn(params, static, xb, yb, tb, wb):
            variables = {**static, "params": params}
            _, logits, new_vars = self._client_apply_train(variables, xb)
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, yb)
            ce = jnp.sum(ce * wb) / jnp.maximum(jnp.sum(wb), 1.0)
            kd = kl_temperature(logits, tb, self.T, wb)
            loss = ce + jnp.where(use_kd, self.alpha, 0.0) * kd
            return loss, new_vars

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        def epoch_body(carry, ekey):
            variables, opt_state = carry
            perm = jax.random.permutation(ekey, self.max_n)
            order = jnp.argsort(1.0 - mask_row[perm], stable=True)
            perm = perm[order]

            def step(carry2, s):
                variables, opt_state = carry2
                take = jax.lax.dynamic_slice_in_dim(
                    perm, s * self.batch_size, self.batch_size
                )
                b_idx = idx_row[take]
                wb = mask_row[take]
                xb = jnp.take(x, b_idx, axis=0)
                yb = jnp.take(y, b_idx, axis=0)
                tb = jnp.take(s_logits, b_idx, axis=0)
                params = variables["params"]
                static = {k: v for k, v in variables.items() if k != "params"}
                (_, new_vars), grads = grad_fn(params, static, xb, yb, tb, wb)
                updates, new_os = self.c_opt.update(grads, opt_state, params)
                new_vars = {
                    **new_vars,
                    "params": optax.apply_updates(params, updates),
                }
                valid = jnp.sum(wb) > 0
                sel = lambda a, b: jax.tree.map(
                    lambda p, q: jnp.where(valid, p, q), a, b
                )
                return (sel(new_vars, variables), sel(new_os, opt_state)), None

            carry2, _ = jax.lax.scan(
                step, (variables, opt_state), jnp.arange(steps)
            )
            return carry2, None

        opt_state = self.c_opt.init(c_vars["params"])
        ekeys = jax.vmap(lambda e: jax.random.fold_in(rng, e))(
            jnp.arange(self.cfg.train.epochs)
        )
        (c_vars, _), _ = jax.lax.scan(epoch_body, (c_vars, opt_state), ekeys)
        return c_vars

    def _round(self, state: FedGKTState, arrays: FederatedArrays):
        n = arrays.num_clients
        rkey = R.round_key(self.root_key, state.round)
        ckeys = jax.vmap(lambda c: R.client_key(rkey, c))(jnp.arange(n))

        # 1. edge training on every client
        client_stack = jax.vmap(
            self._client_phase,
            in_axes=(0, 0, 0, None, None, None, None, 0),
        )(
            state.client_stack, arrays.idx, arrays.mask, arrays.x, arrays.y,
            state.server_logits, state.has_server_logits, ckeys,
        )

        # 2+3. server training, streaming client-by-client. The reference
        #    banks every client's feature maps host-side and iterates
        #    client-per-epoch (``GKTServerTrainer.train_and_eval``); a
        #    device-resident [N_total, H, W, C] bank is ~GBs of HBM at
        #    CIFAR/ResNet scale, so instead the server RECOMPUTES each
        #    batch's features from the (frozen, post-phase-1) edge model —
        #    HBM stays bounded by one batch, and the extra stem forward is
        #    tiny next to the Bottleneck-trunk fwd+bwd.
        #    Loss = KL(teacher=client logits) + alpha*CE
        #    (``GKTServerTrainer.py:48-49,255-263``).
        s_bs = self.batch_size
        s_steps = self.max_n // s_bs

        def s_loss_fn(params, static, fb, yb, tb, wb):
            variables = {**static, "params": params}
            out, new_vars = self._server_apply_train(variables, fb)
            kd = kl_temperature(out, tb, self.T, wb)
            ce = optax.softmax_cross_entropy_with_integer_labels(out, yb)
            ce = jnp.sum(ce * wb) / jnp.maximum(jnp.sum(wb), 1.0)
            return kd + self.alpha * ce, new_vars

        s_grad = jax.value_and_grad(s_loss_fn, has_aux=True)
        skey = jax.random.fold_in(rkey, 0x5EAF)

        def s_client_pass(carry, inputs):
            """One client's epoch slice of server training: recompute the
            client's features batch-by-batch, server grad step on each."""
            variables, opt_state = carry
            c_vars, idx_row, mask_row, ckey = inputs
            perm = jax.random.permutation(ckey, self.max_n)
            order = jnp.argsort(1.0 - mask_row[perm], stable=True)
            perm = perm[order]

            def step(carry2, s):
                variables, opt_state = carry2
                take = jax.lax.dynamic_slice_in_dim(perm, s * s_bs, s_bs)
                b_idx = idx_row[take]
                wb = mask_row[take]
                xb = jnp.take(arrays.x, b_idx, axis=0)
                yb = jnp.take(arrays.y, b_idx, axis=0)
                fb, tb = self._client_apply_eval(c_vars, xb)
                params = variables["params"]
                static = {
                    k: v for k, v in variables.items() if k != "params"
                }
                (_, new_vars), grads = s_grad(params, static, fb, yb, tb, wb)
                updates, new_os = self.s_opt.update(
                    grads, opt_state, params
                )
                new_vars = {
                    **new_vars,
                    "params": optax.apply_updates(params, updates),
                }
                valid = jnp.sum(wb) > 0
                sel = lambda a, b: jax.tree.map(
                    lambda p, q: jnp.where(valid, p, q), a, b
                )
                return (sel(new_vars, variables), sel(new_os, opt_state)), None

            carry2, _ = jax.lax.scan(
                step, (variables, opt_state), jnp.arange(s_steps)
            )
            return carry2, None

        def s_epoch(carry, ekey):
            ckeys_e = jax.vmap(lambda c: jax.random.fold_in(ekey, c))(
                jnp.arange(n)
            )
            carry, _ = jax.lax.scan(
                s_client_pass, carry,
                (client_stack, arrays.idx, arrays.mask, ckeys_e),
            )
            return carry, None

        ekeys = jax.vmap(lambda e: jax.random.fold_in(skey, e))(
            jnp.arange(self.cfg.train.epochs)
        )
        (server_vars, server_os), _ = jax.lax.scan(
            s_epoch, (state.server_vars, state.server_opt_state), ekeys
        )

        # 4. server logits back to clients (GKTServerTrainer
        #    get_global_logits): recompute features per client batch and
        #    scatter logits into the [N_total, K] bank (small: K floats per
        #    sample). Padded rows route to a scratch slot.
        def srv_logits_client(bank, inputs):
            c_vars, idx_row, mask_row = inputs

            def body(bank, s):
                take = jax.lax.dynamic_slice_in_dim(
                    idx_row, s * s_bs, s_bs
                )
                wb = jax.lax.dynamic_slice_in_dim(mask_row, s * s_bs, s_bs)
                xb = jnp.take(arrays.x, take, axis=0)
                fb, _ = self._client_apply_eval(c_vars, xb)
                out = self._server_apply_eval(server_vars, fb)
                safe = jnp.where(wb > 0, take, self.n_total).astype(
                    jnp.int32
                )
                return bank.at[safe].set(out), None

            bank, _ = jax.lax.scan(body, bank, jnp.arange(s_steps))
            return bank, None

        bank0 = jnp.zeros((self.n_total + 1, self.num_classes))
        bank, _ = jax.lax.scan(
            srv_logits_client, bank0,
            (client_stack, arrays.idx, arrays.mask),
        )
        new_server_logits = bank[: self.n_total]

        return (
            FedGKTState(
                client_stack, server_vars, server_os, new_server_logits,
                jnp.asarray(True), state.round + 1,
            ),
            {},
        )

    # -- public API ---------------------------------------------------------
    def init(self) -> FedGKTState:
        k = jax.random.fold_in(self.root_key, 0x7FFFFFFF)
        kc, ks = jax.random.split(k)
        client_stack = vmap_init(
            self._client_init, kc, self.arrays.num_clients
        )
        c0 = jax.tree.map(lambda s: s[0], client_stack)
        f, _ = self._client_apply_eval(
            c0, jnp.zeros((1,) + tuple(self.input_shape))
        )
        server_vars = self._server_init(ks, f.shape[1:])
        return FedGKTState(
            client_stack=client_stack,
            server_vars=server_vars,
            server_opt_state=self.s_opt.init(server_vars["params"]),
            server_logits=jnp.zeros((self.n_total, self.num_classes)),
            has_server_logits=jnp.asarray(False),
            round=jnp.asarray(0, jnp.int32),
        )

    def run_round(self, state: FedGKTState):
        return self._round_fn(state, self.arrays)

    def evaluate(self, state: FedGKTState, client_idx: int = 0) -> dict:
        """End-to-end eval: edge extractor -> server model (reference
        evaluates the composed edge+server path on test data,
        ``GKTServerTrainer.py:299-310``)."""
        c_vars = jax.tree.map(lambda s: s[client_idx], state.client_stack)
        bs = 256
        x, y = self.arrays.test_x, self.arrays.test_y
        n = x.shape[0]
        correct = total = 0
        for s in range(0, n, bs):
            xb, yb = x[s:s + bs], y[s:s + bs]
            f, _ = self._client_apply_eval(c_vars, xb)
            out = self._server_apply_eval(state.server_vars, f)
            correct += int(jnp.sum(jnp.argmax(out, -1) == yb))
            total += xb.shape[0]
        return {"test_acc": correct / max(total, 1)}


# ---------------------------------------------------------------------------
# SplitNN
# ---------------------------------------------------------------------------


class SplitNNState(NamedTuple):
    client_stack: Pytree  # [N, ...] lower stacks (per client)
    server_vars: Pytree
    server_opt_state: Any
    round: jax.Array


class SplitNNSim:
    """Split learning ring: clients sequentially train their epoch; every
    batch does fwd acts -> server loss -> grads back across the cut
    (``split_nn/client.py:24-34``, ``server.py:40-57``). The server weights
    and optimizer state persist around the ring."""

    def __init__(
        self,
        client_model,  # lower module
        server_model,  # upper module
        data: FederatedData,
        cfg: ExperimentConfig,
    ):
        self.client_model = client_model
        self.server_model = server_model
        self.cfg = cfg
        self.arrays, self.batch_size = arrays_and_batch(data, cfg.data)
        self.max_n = self.arrays.max_client_samples
        self.input_shape = self.arrays.x.shape[1:]
        self.c_opt = make_client_optimizer(cfg.train)
        self.s_opt = make_client_optimizer(cfg.train)
        self.root_key = jax.random.key(cfg.seed)
        self._round_fn = jax.jit(self._round, donate_argnums=(0,))

    def _client_init(self, rng):
        dummy = jnp.zeros((1,) + tuple(self.input_shape), jnp.float32)
        return self.client_model.init({"params": rng}, dummy, train=False)

    def _round(self, state: SplitNNState, arrays: FederatedArrays):
        """One ring pass (reference: each client trains an epoch then hands
        the semaphore to node_right, ``client.py:12-13``)."""
        n = arrays.num_clients
        rkey = R.round_key(self.root_key, state.round)
        steps = self.max_n // self.batch_size

        def joint_loss(c_params, s_params, c_static, s_static, xb, yb, wb):
            c_vars = {**c_static, "params": c_params}
            s_vars = {**s_static, "params": s_params}
            acts = self.client_model.apply(c_vars, xb, train=True)
            logits = self.server_model.apply(s_vars, acts, train=True)
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, yb)
            loss = jnp.sum(ce * wb) / jnp.maximum(jnp.sum(wb), 1.0)
            correct = jnp.sum(
                (jnp.argmax(logits, -1) == yb).astype(jnp.float32) * wb
            )
            return loss, correct

        grad_fn = jax.value_and_grad(joint_loss, argnums=(0, 1), has_aux=True)

        def one_client(carry, c):
            server_vars, server_os, loss_sum, correct_sum, n_sum = carry
            c_vars = stack_gather(state.client_stack, c)
            idx_row = arrays.idx[c]
            mask_row = arrays.mask[c]
            ckey = R.client_key(rkey, c)
            c_os = self.c_opt.init(c_vars["params"])

            def step(carry2, s):
                c_vars, c_os, server_vars, server_os, ls, cs, ns = carry2
                perm = jax.random.permutation(ckey, self.max_n)
                order = jnp.argsort(1.0 - mask_row[perm], stable=True)
                take = jax.lax.dynamic_slice_in_dim(
                    perm[order], s * self.batch_size, self.batch_size
                )
                b_idx = idx_row[take]
                wb = mask_row[take]
                xb = jnp.take(arrays.x, b_idx, axis=0)
                yb = jnp.take(arrays.y, b_idx, axis=0)
                cp, sp = c_vars["params"], server_vars["params"]
                c_static = {
                    k: v for k, v in c_vars.items() if k != "params"
                }
                s_static = {
                    k: v for k, v in server_vars.items() if k != "params"
                }
                (loss, correct), (cg, sg) = grad_fn(
                    cp, sp, c_static, s_static, xb, yb, wb
                )
                cu, new_c_os = self.c_opt.update(cg, c_os, cp)
                su, new_s_os = self.s_opt.update(sg, server_os, sp)
                new_c = {**c_vars, "params": optax.apply_updates(cp, cu)}
                new_s = {
                    **server_vars, "params": optax.apply_updates(sp, su)
                }
                valid = jnp.sum(wb) > 0
                sel = lambda a, b: jax.tree.map(
                    lambda p, q: jnp.where(valid, p, q), a, b
                )
                return (
                    sel(new_c, c_vars), sel(new_c_os, c_os),
                    sel(new_s, server_vars), sel(new_s_os, server_os),
                    ls + jnp.where(valid, loss, 0.0), cs + correct,
                    ns + jnp.sum(wb),
                ), None

            (c_vars, _, server_vars, server_os, loss_sum, correct_sum,
             n_sum), _ = jax.lax.scan(
                step,
                (c_vars, c_os, server_vars, server_os, loss_sum,
                 correct_sum, n_sum),
                jnp.arange(steps),
            )
            return (server_vars, server_os, loss_sum, correct_sum, n_sum), c_vars

        # sequential ring as ONE lax.scan over clients: compile time and
        # program size are O(1) in the client count (the previous python
        # loop unrolled O(N) copies of the epoch body); scan stacks each
        # client's updated variables as its per-step output
        (server_vars, server_os, loss_sum, correct_sum, n_sum), new_stack = (
            jax.lax.scan(
                one_client,
                (
                    state.server_vars,
                    state.server_opt_state,
                    jnp.asarray(0.0),
                    jnp.asarray(0.0),
                    jnp.asarray(0.0),
                ),
                jnp.arange(n),
            )
        )
        metrics = {
            "train_loss": loss_sum / (n * steps),
            "train_acc": correct_sum / jnp.maximum(n_sum, 1.0),
        }
        return (
            SplitNNState(new_stack, server_vars, server_os, state.round + 1),
            metrics,
        )

    def init(self) -> SplitNNState:
        k = jax.random.fold_in(self.root_key, 0x7FFFFFFF)
        kc, ks = jax.random.split(k)
        client_stack = vmap_init(
            self._client_init, kc, self.arrays.num_clients
        )
        c0 = jax.tree.map(lambda s: s[0], client_stack)
        acts = self.client_model.apply(
            c0, jnp.zeros((1,) + tuple(self.input_shape)), train=False
        )
        server_vars = self.server_model.init(
            {"params": ks}, acts, train=False
        )
        return SplitNNState(
            client_stack=client_stack,
            server_vars=server_vars,
            server_opt_state=self.s_opt.init(server_vars["params"]),
            round=jnp.asarray(0, jnp.int32),
        )

    def run_round(self, state: SplitNNState):
        return self._round_fn(state, self.arrays)

    def evaluate(
        self, state: SplitNNState, client_idx: int = 0, batch: int = 256
    ) -> dict:
        """Composed lower+upper stack accuracy, batched so the test set
        never materializes one giant activation tensor."""
        c_vars = jax.tree.map(
            lambda s: s[client_idx], state.client_stack
        )
        x, y = self.arrays.test_x, self.arrays.test_y
        correct = total = 0
        for s in range(0, x.shape[0], batch):
            xb, yb = x[s:s + batch], y[s:s + batch]
            acts = self.client_model.apply(c_vars, xb, train=False)
            out = self.server_model.apply(
                state.server_vars, acts, train=False
            )
            correct += int(jnp.sum(jnp.argmax(out, -1) == yb))
            total += xb.shape[0]
        return {"test_acc": correct / max(total, 1)}


# ---------------------------------------------------------------------------
# Classical vertical FL
# ---------------------------------------------------------------------------


class VFLState(NamedTuple):
    party_vars: tuple  # per-party (local_model, dense_model) variables
    opt_states: tuple
    step: jax.Array


class VFLSim:
    """Vertical (feature-partitioned) logistic FL: the guest (party 0)
    holds the labels; every party contributes a logit component computed
    from its feature slice; loss = BCE(sum of components)
    (``vfl.py:21-75``, ``vfl_fixture.py``). Metrics follow the reference's
    sklearn accuracy/AUC on sigmoid(sum)."""

    def __init__(
        self,
        party_models: Sequence[tuple],  # [(local_module, dense_module), ...]
        feature_splits: Sequence[tuple[int, int]],  # col ranges per party
        x_train: np.ndarray,
        y_train: np.ndarray,
        x_test: np.ndarray,
        y_test: np.ndarray,
        cfg: ExperimentConfig,
    ):
        assert len(party_models) == len(feature_splits)
        self.party_models = party_models
        self.splits = list(feature_splits)
        self.cfg = cfg
        self.x_train = jnp.asarray(x_train, jnp.float32)
        self.y_train = jnp.asarray(y_train, jnp.float32)
        self.x_test = jnp.asarray(x_test, jnp.float32)
        self.y_test = jnp.asarray(y_test, jnp.float32)
        self.batch_size = cfg.data.batch_size
        self.opt = make_client_optimizer(cfg.train)
        self.root_key = jax.random.key(cfg.seed)
        self._step_fn = jax.jit(self._step, donate_argnums=(0,))

    def _slice(self, x, p):
        lo, hi = self.splits[p]
        return x[:, lo:hi]

    def _party_logit(self, variables, p, xb, train):
        local_m, dense_m = self.party_models[p]
        lv, dv = variables
        h = local_m.apply(lv, xb, train=train)
        return dense_m.apply(dv, h, train=train)[:, 0]

    def init(self) -> VFLState:
        k = jax.random.fold_in(self.root_key, 0x7FFFFFFF)
        pv, os_ = [], []
        for p, (local_m, dense_m) in enumerate(self.party_models):
            kp = jax.random.fold_in(k, p)
            k1, k2 = jax.random.split(kp)
            xb = self._slice(self.x_train[:1], p)
            lv = local_m.init({"params": k1}, xb, train=False)
            h = local_m.apply(lv, xb, train=False)
            dv = dense_m.init({"params": k2}, h, train=False)
            pv.append((lv, dv))
            os_.append(
                (
                    self.opt.init(lv["params"]),
                    self.opt.init(dv["params"]),
                )
            )
        return VFLState(tuple(pv), tuple(os_), jnp.asarray(0, jnp.int32))

    def _step(self, state: VFLState, xb, yb):
        """One joint batch step. The guest's sum-of-components BCE makes the
        'common gradient' d loss/d component identical for every party
        (``party_models.py`` receive_gradients) — autodiff through the sum
        reproduces exactly that protocol."""

        def loss_fn(all_params):
            total = 0.0
            for p in range(len(self.party_models)):
                lv0, dv0 = state.party_vars[p]
                lp, dp = all_params[p]
                lv = {**lv0, "params": lp}
                dv = {**dv0, "params": dp}
                total = total + self._party_logit(
                    (lv, dv), p, self._slice(xb, p), True
                )
            bce = optax.sigmoid_binary_cross_entropy(total, yb)
            return jnp.mean(bce), total

        all_params = tuple(
            (lv["params"], dv["params"]) for lv, dv in state.party_vars
        )
        (loss, logit_sum), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(all_params)

        new_pv, new_os = [], []
        for p in range(len(self.party_models)):
            lv, dv = state.party_vars[p]
            lo, do = state.opt_states[p]
            lg, dg = grads[p]
            lu, new_lo = self.opt.update(lg, lo, lv["params"])
            du, new_do = self.opt.update(dg, do, dv["params"])
            new_pv.append(
                (
                    {**lv, "params": optax.apply_updates(lv["params"], lu)},
                    {**dv, "params": optax.apply_updates(dv["params"], du)},
                )
            )
            new_os.append((new_lo, new_do))
        return (
            VFLState(tuple(new_pv), tuple(new_os), state.step + 1),
            loss,
        )

    def run_round(self, state: VFLState):
        """Harness protocol adapter: one VFL "round" = one epoch over the
        aligned feature-partitioned batches (the reference's epoch loop,
        ``classical_vertical_fl/vfl_fixture.py``)."""
        state, loss = self.run_epoch(state)
        return state, {"train_loss": loss}

    def run_epoch(self, state: VFLState) -> tuple[VFLState, float]:
        n = self.x_train.shape[0]
        bs = self.batch_size
        rng = np.random.default_rng(int(state.step))
        perm = rng.permutation(n)
        losses = []
        for s in range(n // bs):
            take = perm[s * bs:(s + 1) * bs]
            state, loss = self._step_fn(
                state, self.x_train[take], self.y_train[take]
            )
            losses.append(float(loss))
        return state, float(np.mean(losses)) if losses else 0.0

    def predict(self, state: VFLState, x) -> jnp.ndarray:
        total = 0.0
        for p in range(len(self.party_models)):
            total = total + self._party_logit(
                state.party_vars[p], p, self._slice(x, p), False
            )
        return jax.nn.sigmoid(total)

    def evaluate(self, state: VFLState) -> dict:
        probs = np.asarray(self.predict(state, self.x_test))
        y = np.asarray(self.y_test)
        acc = float(np.mean((probs > 0.5) == (y > 0.5)))
        # AUC (reference vfl_fixture logs sklearn roc_auc_score)
        order = np.argsort(probs)
        ranks = np.empty_like(order, dtype=np.float64)
        ranks[order] = np.arange(1, len(probs) + 1)
        pos = y > 0.5
        n_pos, n_neg = pos.sum(), (~pos).sum()
        auc = (
            (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)
            if n_pos and n_neg
            else float("nan")
        )
        return {"test_acc": acc, "test_auc": float(auc)}

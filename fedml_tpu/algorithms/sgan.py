"""Semi-supervised and universally-aggregated federated GANs.

- :class:`FedSSGANSim` — federated semi-supervised GAN (reference
  ``fedml_api/standalone/federated_sgan/``): each client holds the shared
  ACGAN (G + classifier-discriminator) and a mix of labelled and
  unlabelled data; the ssgan logsumexp losses apply the supervised
  auxiliary term only where labels exist; the WHOLE model (G+D) is
  FedAvg-aggregated (``fedssgan_api.py:62-100``). Clients can synthesize
  extra unlabelled data filtered by classifier confidence
  (``model_trainer.py:317-340`` ``generate_synthetic_dataset`` with a
  realism threshold).
- :class:`FedUAGANSim` — UA-GAN (reference
  ``fedml_api/standalone/federated_uagan/server.py:74-146``): ONE central
  conditional generator; clients keep private ACGAN discriminators trained
  on local real + central fakes; the generator step backpropagates through
  the sample-count-weighted AVERAGE of all client discriminator outputs
  (the "universal" discriminator). There is no discriminator averaging —
  knowledge flows only through the aggregated outputs, so it maps onto TPU
  as a vmapped per-client discriminator bank with a weighted mean over the
  client axis inside one differentiable program.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.algorithms import gan_core as G
from fedml_tpu.algorithms.base import make_client_optimizer
from fedml_tpu.config import ExperimentConfig
from fedml_tpu.core import random as R
from fedml_tpu.core import tree as T
from fedml_tpu.algorithms.stack_utils import (
    size_grouped_lanes as _size_grouped_lanes,
    vmap_init,
)
from fedml_tpu.data.federated import FederatedArrays, FederatedData, arrays_and_batch
from fedml_tpu.models.gan import GanModel

Pytree = Any


class FedSSGANState(NamedTuple):
    gen_vars: Pytree
    disc_vars: Pytree
    round: jax.Array


class FedSSGANSim:
    """Semi-supervised federated ACGAN. ``label_fraction`` of each client's
    samples keep labels; the rest contribute only adversarial terms."""

    def __init__(
        self,
        gen: GanModel,
        disc: G.DiscHandle,
        data: FederatedData,
        cfg: ExperimentConfig,
        label_fraction: float = 0.5,
    ):
        self.gen, self.disc, self.cfg = gen, disc, cfg
        self.arrays, self.batch_size = arrays_and_batch(data, cfg.data)
        self.max_n = self.arrays.max_client_samples
        self.input_shape = self.arrays.x.shape[1:]
        self.label_fraction = float(label_fraction)
        # per-sample labelled mask over the GLOBAL train array, seeded so
        # the labelled subset is fixed across rounds
        mask_rng = jax.random.uniform(
            jax.random.key(cfg.seed ^ 0x55), (self.arrays.x.shape[0],)
        )
        self.labelled = (mask_rng < self.label_fraction).astype(jnp.float32)
        self.root_key = jax.random.key(cfg.seed)
        self.local_update = self._build_local_update()
        self._round_fn = jax.jit(self._round, donate_argnums=(0,))

    def _build_local_update(self):
        gen, disc = self.gen, self.disc
        cfg_t, cfg_g = self.cfg.train, self.cfg.gan
        batch_size, max_n = self.batch_size, self.max_n
        steps = max_n // batch_size
        g_opt = G.make_gen_optimizer(cfg_g)
        d_opt = make_client_optimizer(cfg_t)
        labelled = self.labelled

        def g_loss_fn(g_params, g_static, d_vars, z, gl, w, rng):
            g_vars = {**g_static, "params": g_params}
            fakes, new_g = gen.apply_train(g_vars, z, gl)
            out, _ = disc.apply_train(d_vars, fakes, rng)
            return G.generator_loss_ssgan(out, gl, w), (new_g, fakes)

        def d_loss_fn(d_params, d_static, fakes, gl, xb, yb, w, lab_w, rng):
            """ssgan D loss with the supervised CE restricted to labelled
            rows (semi-supervised GAN: unlabelled real data only feeds the
            adversarial logsumexp terms)."""
            d_vars = {**d_static, "params": d_params}
            r1, r2 = jax.random.split(rng)
            cls_fake, d1 = disc.apply_train(d_vars, fakes, r1)
            cls_real, d2 = disc.apply_train(d1, xb, r2)
            logz_f = jax.nn.logsumexp(cls_fake, axis=-1)
            fake_half = 0.5 * (
                G._ce(cls_fake, gl, w)
                + G._masked_mean(jax.nn.softplus(logz_f), w)
            )
            logz_r = jax.nn.logsumexp(cls_real, axis=-1)
            real_half = 0.5 * (
                G._ce(cls_real, yb, lab_w)  # supervised: labelled only
                + G._masked_mean(
                    -logz_r + jax.nn.softplus(logz_r), w
                )  # adversarial: all real rows
            )
            return fake_half + real_half, d2

        g_grad = jax.value_and_grad(g_loss_fn, has_aux=True)
        d_grad = jax.value_and_grad(d_loss_fn, has_aux=True)

        def update(gen_vars, disc_vars, idx_row, mask_row, x, y, rng):
            def epoch_body(carry, ekey):
                g_vars, d_vars, g_os, d_os = carry
                perm = jax.random.permutation(ekey, max_n)
                order = jnp.argsort(1.0 - mask_row[perm], stable=True)
                perm = perm[order]

                def step(carry2, s):
                    g_vars, d_vars, g_os, d_os = carry2
                    take = jax.lax.dynamic_slice_in_dim(
                        perm, s * batch_size, batch_size
                    )
                    b_idx = idx_row[take]
                    wb = mask_row[take]
                    lab_w = wb * labelled[b_idx]
                    xb = jnp.take(x, b_idx, axis=0)
                    yb = jnp.take(y, b_idx, axis=0)
                    skey = jax.random.fold_in(ekey, s)
                    kz, kl, k1, k2 = jax.random.split(skey, 4)
                    z = gen.sample_noise(kz, batch_size)
                    gl = gen.sample_labels(kl, batch_size)

                    gp = g_vars["params"]
                    gs = {k: v for k, v in g_vars.items() if k != "params"}
                    (_, (new_g, fakes)), ggr = g_grad(
                        gp, gs, d_vars, z, gl, wb, k1
                    )
                    gu, new_g_os = g_opt.update(ggr, g_os, gp)
                    new_g = {**new_g, "params": optax.apply_updates(gp, gu)}

                    dp = d_vars["params"]
                    ds = {k: v for k, v in d_vars.items() if k != "params"}
                    (_, new_d), dgr = d_grad(
                        dp, ds, jax.lax.stop_gradient(fakes), gl, xb, yb,
                        wb, lab_w, k2,
                    )
                    du, new_d_os = d_opt.update(dgr, d_os, dp)
                    new_d = {**new_d, "params": optax.apply_updates(dp, du)}

                    valid = jnp.sum(wb) > 0
                    sel = lambda a, b: jax.tree.map(
                        lambda p, q: jnp.where(valid, p, q), a, b
                    )
                    return (
                        sel(new_g, g_vars), sel(new_d, d_vars),
                        sel(new_g_os, g_os), sel(new_d_os, d_os),
                    )

                n_steps = G.dynamic_trip_count(mask_row, batch_size, steps)
                carry2 = jax.lax.fori_loop(
                    0, n_steps, lambda i, c: step(c, i),
                    (g_vars, d_vars, g_os, d_os),
                )
                return carry2, None

            g_os = g_opt.init(gen_vars["params"])
            d_os = d_opt.init(disc_vars["params"])
            ekeys = jax.vmap(lambda e: jax.random.fold_in(rng, e))(
                jnp.arange(cfg_t.epochs)
            )
            (g_vars, d_vars, _, _), _ = jax.lax.scan(
                epoch_body, (gen_vars, disc_vars, g_os, d_os), ekeys
            )
            return g_vars, d_vars, jnp.sum(mask_row)

        return update

    def init(self) -> FedSSGANState:
        k = jax.random.fold_in(self.root_key, 0x7FFFFFFF)
        kg, kd = jax.random.split(k)
        return FedSSGANState(
            gen_vars=self.gen.init(kg),
            disc_vars=self.disc.init(kd, self.input_shape),
            round=jnp.asarray(0, jnp.int32),
        )

    def _round(self, state: FedSSGANState, arrays: FederatedArrays):
        cfg = self.cfg.fed
        rkey = R.round_key(self.root_key, state.round)
        cohort = R.sample_clients(
            jax.random.fold_in(rkey, 0), arrays.num_clients,
            cfg.clients_per_round,
        )
        ckeys = jax.vmap(lambda c: R.client_key(rkey, c))(cohort)
        mask_rows = arrays.mask[cohort]
        g_stack, d_stack, n_k = _size_grouped_lanes(
            lambda idxs, masks, keys: jax.vmap(
                self.local_update, in_axes=(None, None, 0, 0, None, None, 0)
            )(
                state.gen_vars, state.disc_vars, idxs, masks,
                arrays.x, arrays.y, keys,
            ),
            (arrays.idx[cohort], mask_rows, ckeys), mask_rows,
            self.cfg.train.cohort_groups,
        )
        # whole-model FedAvg (fedssgan_api.py:96-100)
        return (
            FedSSGANState(
                T.tree_weighted_mean(g_stack, n_k),
                T.tree_weighted_mean(d_stack, n_k),
                state.round + 1,
            ),
            {},
        )

    def run_round(self, state: FedSSGANState):
        return self._round_fn(state, self.arrays)

    def generate_synthetic_dataset(
        self, state: FedSSGANState, target_size: int, seed: int = 0
    ):
        """Confidence-filtered synthetic data with pseudo-labels (reference
        ``generate_synthetic_dataset``, ``model_trainer.py:322-340``):
        returns (images, pseudo_labels, keep_mask) — static shapes, with the
        sub-threshold rows masked out rather than dropped."""
        k = jax.random.key(seed)
        z = self.gen.sample_noise(k, target_size)
        gl = self.gen.sample_labels(jax.random.fold_in(k, 1), target_size)
        imgs = self.gen.apply_eval(state.gen_vars, z, gl)
        logits = self.disc.apply_eval(state.disc_vars, imgs)
        probs = jax.nn.softmax(logits, axis=-1)
        conf = jnp.max(probs, axis=-1)
        pseudo = jnp.argmax(probs, axis=-1)
        keep = conf >= self.cfg.gan.pseudo_label_threshold
        return imgs, pseudo, keep


class FedUAGANState(NamedTuple):
    gen_vars: Pytree
    gen_opt_state: Any
    disc_stack: Pytree  # [N, ...] private client discriminators
    round: jax.Array


class FedUAGANSim:
    """UA-GAN: central generator vs a bank of private client
    discriminators whose outputs are weight-averaged for the G update."""

    REAL_LABEL = 1.0

    def __init__(
        self,
        gen: GanModel,
        disc: G.DiscHandle,
        data: FederatedData,
        cfg: ExperimentConfig,
    ):
        assert disc.has_validity_head, "UA-GAN needs an ACGAN discriminator"
        self.gen, self.disc, self.cfg = gen, disc, cfg
        self.arrays, self.batch_size = arrays_and_batch(data, cfg.data)
        self.max_n = self.arrays.max_client_samples
        self.input_shape = self.arrays.x.shape[1:]
        self.g_opt = G.make_gen_optimizer(cfg.gan)
        self.root_key = jax.random.key(cfg.seed)
        self.disc_update = self._build_disc_update()
        self._round_fn = jax.jit(self._round, donate_argnums=(0,))

    def _build_disc_update(self):
        """Client discriminator epoch: ACGAN D losses on local real data vs
        a server-provided fake batch (``federated_uagan/server.py:88-103``,
        client ``train``)."""
        disc = self.disc
        cfg_t = self.cfg.train
        batch_size, max_n = self.batch_size, self.max_n
        steps = max_n // batch_size
        d_opt = make_client_optimizer(cfg_t)

        def loss_fn(d_params, d_static, fakes, gl, xb, yb, wb, rng):
            d_vars = {**d_static, "params": d_params}
            r1, r2 = jax.random.split(rng)
            (cls_r, v_r), d1 = disc.apply_train(d_vars, xb, r1, validity=True)
            (cls_f, v_f), d2 = disc.apply_train(
                d1, fakes, r2, validity=True
            )
            loss = G.discriminator_loss_acgan(
                cls_f, v_f, gl, cls_r, v_r, yb, wb
            )
            return loss, d2

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        def update(d_vars, fakes, gl, idx_row, mask_row, x, y, rng):
            opt_state = d_opt.init(d_vars["params"])

            def step(carry, s):
                d_vars, opt_state = carry
                perm_key = jax.random.fold_in(rng, s)
                take = jax.random.permutation(perm_key, max_n)[:batch_size]
                b_idx = idx_row[take]
                wb = mask_row[take]
                xb = jnp.take(x, b_idx, axis=0)
                yb = jnp.take(y, b_idx, axis=0)
                dp = d_vars["params"]
                ds = {k: v for k, v in d_vars.items() if k != "params"}
                (_, new_d), grads = grad_fn(
                    dp, ds, fakes, gl, xb, yb, wb,
                    jax.random.fold_in(rng, 1000 + s),
                )
                updates, new_os = d_opt.update(grads, opt_state, dp)
                new_d = {
                    **new_d, "params": optax.apply_updates(dp, updates)
                }
                valid = jnp.sum(wb) > 0
                sel = lambda a, b: jax.tree.map(
                    lambda p, q: jnp.where(valid, p, q), a, b
                )
                return (sel(new_d, d_vars), sel(new_os, opt_state)), None

            (d_vars, _), _ = jax.lax.scan(
                step, (d_vars, opt_state), jnp.arange(steps)
            )
            return d_vars

        return update

    def init(self) -> FedUAGANState:
        k = jax.random.fold_in(self.root_key, 0x7FFFFFFF)
        kg, kd = jax.random.split(k)
        gen_vars = self.gen.init(kg)
        return FedUAGANState(
            gen_vars=gen_vars,
            gen_opt_state=self.g_opt.init(gen_vars["params"]),
            disc_stack=vmap_init(
                lambda k: self.disc.init(k, self.input_shape), kd,
                self.arrays.num_clients,
            ),
            round=jnp.asarray(0, jnp.int32),
        )

    def _round(self, state: FedUAGANState, arrays: FederatedArrays):
        rkey = R.round_key(self.root_key, state.round)
        n = arrays.num_clients
        ckeys = jax.vmap(lambda c: R.client_key(rkey, c))(jnp.arange(n))
        counts = arrays.counts.astype(jnp.float32)

        # --- discriminator phase: fakes from the CURRENT generator ---
        kz = jax.random.fold_in(rkey, 1)
        z = self.gen.sample_noise(kz, self.batch_size)
        gl = self.gen.sample_labels(jax.random.fold_in(rkey, 2),
                                    self.batch_size)
        fakes = jax.lax.stop_gradient(
            self.gen.apply_eval(state.gen_vars, z, gl)
        )
        disc_stack = jax.vmap(
            self.disc_update, in_axes=(0, None, None, 0, 0, None, None, 0)
        )(
            state.disc_stack, fakes, gl, arrays.idx, arrays.mask,
            arrays.x, arrays.y, ckeys,
        )

        # --- generator phase: grad through the weighted-average D output
        #     (server.py:105-128, _calculate_D_ua) ---
        z2 = self.gen.sample_noise(jax.random.fold_in(rkey, 3),
                                   self.batch_size)
        gl2 = self.gen.sample_labels(jax.random.fold_in(rkey, 4),
                                     self.batch_size)

        def g_loss_fn(g_params, g_static):
            g_vars = {**g_static, "params": g_params}
            fakes2, _ = self.gen.apply_train(g_vars, z2, gl2)

            def one_disc(d_vars):
                cls, val = self.disc.apply_eval(d_vars, fakes2, validity=True)
                # reference averages post-sigmoid probabilities
                # (utils/gradient.py weighted outputs); we average
                # probabilities then convert back to a logit for the BCE
                return jax.nn.sigmoid(val), jax.nn.softmax(cls, axis=-1)

            probs, cls_probs = jax.vmap(one_disc)(disc_stack)
            w = counts / jnp.sum(counts)
            ua_prob = jnp.einsum("c,cbo->bo", w, probs).clip(1e-6, 1 - 1e-6)
            ua_cls = jnp.einsum("c,cbk->bk", w, cls_probs).clip(1e-9)
            adv = -jnp.mean(
                self.REAL_LABEL * jnp.log(ua_prob)
                + (1 - self.REAL_LABEL) * jnp.log1p(-ua_prob)
            )
            aux = -jnp.mean(
                jnp.log(ua_cls[jnp.arange(gl2.shape[0]), gl2])
            )
            return 0.5 * (adv + aux)

        gp = state.gen_vars["params"]
        gs = {k: v for k, v in state.gen_vars.items() if k != "params"}
        g_loss, ggr = jax.value_and_grad(g_loss_fn)(gp, gs)
        gu, new_g_os = self.g_opt.update(ggr, state.gen_opt_state, gp)
        new_gen = {**state.gen_vars, "params": optax.apply_updates(gp, gu)}

        return (
            FedUAGANState(
                new_gen, new_g_os, disc_stack, state.round + 1
            ),
            {"g_loss": g_loss},
        )

    def run_round(self, state: FedUAGANState):
        return self._round_fn(state, self.arrays)

    def sample_images(self, state: FedUAGANState, n: int, seed: int = 0):
        k = jax.random.key(seed)
        z = self.gen.sample_noise(k, n)
        gl = self.gen.balanced_labels(n)
        return self.gen.apply_eval(state.gen_vars, z, gl)

"""FL algorithms.

Compiled-simulation algorithms (the TPU redesign of the reference's
``fedml_api/standalone`` family) plus actor-based distributed variants
(redesign of ``fedml_api/distributed``). The compiled path expresses a whole
federated round as one XLA program: cohort sampling, vmapped local SGD,
weighted pytree aggregation, and the server update.
"""

from fedml_tpu.algorithms.base import Task, build_evaluator, build_local_update, make_task
from fedml_tpu.algorithms.fedavg import FedAvgSim, ServerState

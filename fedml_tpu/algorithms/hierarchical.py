"""Hierarchical FL: cloud -> edge-group -> client two-level aggregation.

Redesign of the reference's ``fedml_api/standalone/hierarchical_fl``
(``trainer.py:8-70``: random client grouping, nested
global-round x group-round x epoch loop; ``group.py:24-46`` group
aggregation) and the cross-silo 2-level pattern.

TPU formulation: clients are grouped into equal-size groups stacked as
``[G, C_g, ...]``. A global round = ``group_comm_round`` inner FedAvg
rounds vmapped over groups (each group aggregates only its own clients),
then a weighted mean over groups. On a mesh this maps to 2-level psum —
intra-submesh then inter-submesh (see SURVEY.md §2.7).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.config import ExperimentConfig
from fedml_tpu.core import random as R
from fedml_tpu.core import tree as T
from fedml_tpu.data.federated import FederatedData, arrays_and_batch
from fedml_tpu.algorithms.base import (
    build_evaluator,
    build_local_update,
    finalize_sums,
    make_task,
)
from fedml_tpu.models.base import FedModel

Pytree = Any


class HierState(NamedTuple):
    variables: Pytree
    round: jax.Array


class HierarchicalFedAvg:
    """Two-level FedAvg (reference ``hierarchical_fl/trainer.py:43-70``)."""

    def __init__(
        self,
        model: FedModel,
        data: FederatedData,
        cfg: ExperimentConfig,
        num_groups: int = 2,
        group_comm_round: int = 1,
    ):
        self.model = model
        self.cfg = cfg
        self.task = make_task(data.task)
        self.arrays, self.batch_size = arrays_and_batch(data, cfg.data)
        n = self.arrays.num_clients
        assert n % num_groups == 0, (n, num_groups)
        self.num_groups = num_groups
        self.group_size = n // num_groups
        self.group_comm_round = group_comm_round
        # random grouping, fixed for the run (trainer.py:13-21)
        rng = np.random.default_rng(cfg.seed)
        self.grouping = jnp.asarray(
            rng.permutation(n).reshape(num_groups, self.group_size)
        )
        max_n = self.arrays.max_client_samples
        self.local_update = build_local_update(
            model, self.task, cfg.train, self.batch_size, max_n
        )
        self.evaluator = build_evaluator(model, self.task)
        self.root_key = jax.random.key(cfg.seed)
        self._round_fn = jax.jit(self._round, donate_argnums=(0,))

    def init(self) -> HierState:
        variables = self.model.init(
            jax.random.fold_in(self.root_key, 0x7FFFFFFF)
        )
        return HierState(variables, jnp.asarray(0, jnp.int32))

    def _round(self, state: HierState, arrays):
        rkey = R.round_key(self.root_key, state.round)

        def group_train(gvars, client_ids, gkey):
            """group_comm_round inner FedAvg rounds over this group's
            clients (reference group.py:24-46)."""

            def inner(carry, r):
                gvars, _ = carry
                ckeys = jax.vmap(
                    lambda c: R.client_key(jax.random.fold_in(gkey, r), c)
                )(client_ids)
                stacked, n_k, msums = jax.vmap(
                    self.local_update, in_axes=(None, 0, 0, None, None, 0)
                )(gvars, arrays.idx[client_ids], arrays.mask[client_ids],
                  arrays.x, arrays.y, ckeys)
                agg = T.tree_weighted_mean(stacked, n_k)
                return (agg, jnp.sum(n_k)), msums

            (gvars, g_n), msums = jax.lax.scan(
                inner, (gvars, jnp.asarray(0.0)),
                jnp.arange(self.group_comm_round),
            )
            return gvars, g_n, jax.tree.map(lambda v: jnp.sum(v), msums)

        gkeys = jax.vmap(lambda g: jax.random.fold_in(rkey, g))(
            jnp.arange(self.num_groups)
        )
        g_vars, g_n, msums = jax.vmap(group_train, in_axes=(None, 0, 0))(
            state.variables, self.grouping, gkeys
        )
        new_vars = T.tree_weighted_mean(g_vars, g_n)
        reduced = jax.tree.map(jnp.sum, msums)
        fin = finalize_sums(reduced)
        return (
            HierState(new_vars, state.round + 1),
            {"train_loss": fin["loss"], "train_acc": fin["acc"]},
        )

    def run_round(self, state):
        return self._round_fn(state, self.arrays)

    def evaluate_global(self, state) -> dict:
        m = self.evaluator(
            state.variables, self.arrays.test_x, self.arrays.test_y
        )
        return {k: float(v) for k, v in m.items()}

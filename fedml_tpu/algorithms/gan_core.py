"""Compiled building blocks for the GAN/KD algorithm family.

The reference trains GANs with a per-batch python loop of alternating
generator/discriminator optimizer steps
(``fedml_api/standalone/fedgdkd/ac_gan_model_trainer.py:52-120`` and the
logsumexp variant ``fedml_api/standalone/fedgdkd/model_trainer.py:23-113``).
Here each client's whole adversarial training run is ONE ``lax.scan`` over
steps (vmappable across the cohort), and the distillation phase is another
scan — so a round of FedGDKD compiles to a single XLA program.

Two adversarial modes:

- ``acgan``: BCE on a dedicated validity head + CE auxiliary classifier
  (reference ``ac_gan_model_trainer.py:52-120``). Requires a discriminator
  module with a ``discriminator=True`` call path (e.g.
  :class:`fedml_tpu.models.gan.ACGANDiscriminator`).
- ``ssgan``: the semi-supervised logsumexp formulation where the
  discriminator IS the client's K-way classifier
  (``fedgdkd/model_trainer.py:23-113``): real/fake confidence is
  ``logsumexp(logits)``; adversarial terms use ``softplus``.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.algorithms import kd as KD
from fedml_tpu.algorithms.base import make_client_optimizer
from fedml_tpu.config import GanConfig, TrainConfig
from fedml_tpu.models.base import FedModel
from fedml_tpu.models.gan import GanModel

Pytree = Any


@dataclasses.dataclass(frozen=True)
class DiscHandle:
    """Functional handle on a discriminator/classifier module that may have
    an auxiliary validity head (``cnn_custom.py:36-41``)."""

    module: Any
    has_batch_stats: bool = True
    has_dropout: bool = True
    has_validity_head: bool = False

    @classmethod
    def from_fed_model(cls, m: FedModel) -> "DiscHandle":
        return cls(
            module=m.module,
            has_batch_stats=m.has_batch_stats,
            has_dropout=m.has_dropout,
            has_validity_head=False,
        )

    def init(self, rng: jax.Array, input_shape) -> Pytree:
        dummy = jnp.zeros((1,) + tuple(input_shape), jnp.float32)
        kwargs = {"discriminator": True} if self.has_validity_head else {}
        return self.module.init({"params": rng}, dummy, train=False, **kwargs)

    def _rngs(self, rng):
        return {"dropout": rng} if self.has_dropout else None

    def apply_train(self, variables, x, rng, validity: bool = False):
        kwargs = {"discriminator": True} if validity else {}
        if self.has_batch_stats:
            out, mutated = self.module.apply(
                variables, x, train=True, rngs=self._rngs(rng),
                mutable=["batch_stats"], **kwargs,
            )
            return out, {**variables, **mutated}
        out = self.module.apply(
            variables, x, train=True, rngs=self._rngs(rng), **kwargs
        )
        return out, variables

    def apply_eval(self, variables, x, validity: bool = False):
        kwargs = {"discriminator": True} if validity else {}
        return self.module.apply(variables, x, train=False, **kwargs)


def make_gen_optimizer(cfg: GanConfig) -> optax.GradientTransformation:
    """Generator optimizer (reference ``gen_optimizer``/``gen_lr`` args,
    ``main_fedgdkd.py:40-45``)."""
    if cfg.gen_optimizer == "adam":
        return optax.adam(cfg.gen_lr)
    if cfg.gen_optimizer == "sgd":
        return optax.sgd(cfg.gen_lr)
    raise ValueError(f"unknown gen optimizer: {cfg.gen_optimizer}")


def make_stacked_gen_optimizer(cfg: GanConfig) -> optax.GradientTransformation:
    """Per-client generator optimizer over STACKED [C, ...] params — the
    cohort-fused GAN update's replacement for ``vmap`` of
    :func:`make_gen_optimizer`. Plain sgd is stateless-per-leaf and
    stacks trivially; adam needs a per-client step COUNT ([C] instead of
    optax's scalar) so a padded step gated out for one client does not
    advance its bias correction. The update mirrors
    ``optax.scale_by_adam``'s expressions term for term (same moment
    recurrences, ``1 - b**count`` bias correction, eps placement), so a
    lane of this transformation is bitwise the per-client
    ``optax.adam``."""
    if cfg.gen_optimizer == "sgd":
        return optax.sgd(cfg.gen_lr)
    if cfg.gen_optimizer != "adam":
        raise ValueError(f"unknown gen optimizer: {cfg.gen_optimizer}")
    lr, b1, b2, eps = cfg.gen_lr, 0.9, 0.999, 1e-8

    def init(params):
        c = jax.tree.leaves(params)[0].shape[0]
        return (
            jnp.zeros((c,), jnp.int32),
            jax.tree.map(jnp.zeros_like, params),
            jax.tree.map(jnp.zeros_like, params),
        )

    def update(grads, state, params=None):
        count, mu, nu = state
        count_inc = count + 1
        mu = jax.tree.map(lambda g, t: (1 - b1) * g + b1 * t, grads, mu)
        nu = jax.tree.map(
            lambda g, t: (1 - b2) * (g * g) + b2 * t, grads, nu
        )

        def upd(m, v):
            shape = (count_inc.shape[0],) + (1,) * (m.ndim - 1)
            mh = m / (1 - b1 ** count_inc).reshape(shape)
            vh = v / (1 - b2 ** count_inc).reshape(shape)
            return -lr * (mh / (jnp.sqrt(vh) + eps))

        return jax.tree.map(upd, mu, nu), (count_inc, mu, nu)

    return optax.GradientTransformation(init, update)


def _masked_mean(v, w):
    return jnp.sum(v * w) / jnp.maximum(jnp.sum(w), 1.0)


def _ce(logits, labels, w):
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    return _masked_mean(ce, w)


# ---------------------------------------------------------------------------
# Adversarial losses
# ---------------------------------------------------------------------------


def generator_loss_ssgan(cls_logits_gen, gen_labels, w):
    """errG of ``fedgdkd/model_trainer.py:44-61``: aux = CE(logits, label);
    adv = mean(-logz + softplus(logz)); errG = (adv + aux) / 2."""
    logz = jax.nn.logsumexp(cls_logits_gen, axis=-1)
    aux = _ce(cls_logits_gen, gen_labels, w)
    adv = _masked_mean(-logz + jax.nn.softplus(logz), w)
    return 0.5 * (adv + aux)


def discriminator_loss_ssgan(cls_fake, gen_labels, cls_real, real_labels, w):
    """errD of ``fedgdkd/model_trainer.py:63-104``."""
    logz_f = jax.nn.logsumexp(cls_fake, axis=-1)
    fake_half = 0.5 * (
        _ce(cls_fake, gen_labels, w)
        + _masked_mean(jax.nn.softplus(logz_f), w)
    )
    logz_r = jax.nn.logsumexp(cls_real, axis=-1)
    real_half = 0.5 * (
        _ce(cls_real, real_labels, w)
        + _masked_mean(-logz_r + jax.nn.softplus(logz_r), w)
    )
    return fake_half + real_half


def _bce_logits(v_logit, target, w):
    # sigmoid+BCELoss == BCE-with-logits (reference applies Sigmoid in the
    # module, cnn_custom.py:40, then BCELoss, ac_gan_model_trainer.py:57)
    b = optax.sigmoid_binary_cross_entropy(v_logit[:, 0], target)
    return _masked_mean(b, w)


def generator_loss_acgan(cls_gen, v_gen, gen_labels, w):
    """errG of ``ac_gan_model_trainer.py:85-97``."""
    return 0.5 * (
        _bce_logits(v_gen, jnp.ones(v_gen.shape[0]), w)
        + _ce(cls_gen, gen_labels, w)
    )


def discriminator_loss_acgan(
    cls_fake, v_fake, gen_labels, cls_real, v_real, real_labels, w
):
    """errD of ``ac_gan_model_trainer.py:99-116``."""
    d_real = 0.5 * (
        _bce_logits(v_real, jnp.ones(v_real.shape[0]), w)
        + _ce(cls_real, real_labels, w)
    )
    d_fake = 0.5 * (
        _bce_logits(v_fake, jnp.zeros(v_fake.shape[0]), w)
        + _ce(cls_fake, gen_labels, w)
    )
    return 0.5 * (d_real + d_fake)


# ---------------------------------------------------------------------------
# The compiled adversarial local update
# ---------------------------------------------------------------------------


def dynamic_trip_count(mask_row, batch_size: int, max_steps: int):
    """Per-lane dynamic step-loop bound: ceil(n_k / B) clamped to the
    static maximum. VALID ONLY when the epoch perm sorts this client's
    real samples first (every GAN-family loop using this does:
    ``argsort(1.0 - mask_row[perm], stable=True)``) — then the skipped
    tail steps are exactly the fully-padded no-op batches. Under vmap
    the bound is per-lane and the batched while runs each call to the
    max over its lanes, which is what ``stack_utils.size_grouped_lanes``
    exploits."""
    return jnp.minimum(
        (jnp.sum(mask_row).astype(jnp.int32) + batch_size - 1)
        // batch_size,
        max_steps,
    )


def build_gan_local_update(
    gen: GanModel,
    disc: DiscHandle,
    train_cfg: TrainConfig,
    gan_cfg: GanConfig,
    batch_size: int,
    max_n: int,
    mode: str = "ssgan",
):
    """Build ``update(gen_vars, disc_vars, idx_row, mask_row, x, y, rng)``
    -> ``(gen_vars, disc_vars, n_k, loss_sums)``.

    One G step then one D step per batch, G first on fresh fakes, D on the
    same fakes without grad flow to G — matching the reference's ordering
    and ``.detach()`` (``ac_gan_model_trainer.py:80-116``).
    """
    assert mode in ("ssgan", "acgan"), mode
    assert max_n % batch_size == 0
    steps_per_epoch = max_n // batch_size
    g_opt = make_gen_optimizer(gan_cfg)
    d_opt = make_client_optimizer(train_cfg)

    def g_loss_fn(g_params, g_static, d_vars, z, gen_labels, w, rng):
        g_vars = {**g_static, "params": g_params}
        fakes, new_g_vars = gen.apply_train(g_vars, z, gen_labels)
        if mode == "ssgan":
            out, _ = disc.apply_train(d_vars, fakes, rng)
            loss = generator_loss_ssgan(out, gen_labels, w)
        else:
            (cls, val), _ = disc.apply_train(d_vars, fakes, rng, validity=True)
            loss = generator_loss_acgan(cls, val, gen_labels, w)
        return loss, (new_g_vars, fakes)

    def d_loss_fn(d_params, d_static, fakes, gen_labels, x_b, y_b, w, rng):
        d_vars = {**d_static, "params": d_params}
        r1, r2 = jax.random.split(rng)
        if mode == "ssgan":
            cls_fake, d_vars1 = disc.apply_train(d_vars, fakes, r1)
            cls_real, d_vars2 = disc.apply_train(d_vars1, x_b, r2)
            loss = discriminator_loss_ssgan(cls_fake, gen_labels, cls_real, y_b, w)
        else:
            (cls_f, v_f), d_vars1 = disc.apply_train(
                d_vars, fakes, r1, validity=True
            )
            (cls_r, v_r), d_vars2 = disc.apply_train(
                d_vars1, x_b, r2, validity=True
            )
            loss = discriminator_loss_acgan(
                cls_f, v_f, gen_labels, cls_r, v_r, y_b, w
            )
        return loss, d_vars2

    g_grad = jax.value_and_grad(g_loss_fn, has_aux=True)
    d_grad = jax.value_and_grad(d_loss_fn, has_aux=True)

    def update(gen_vars, disc_vars, idx_row, mask_row, x, y, rng):
        def epoch_body(carry, ekey):
            g_vars, d_vars, g_os, d_os, sums = carry
            perm = jax.random.permutation(ekey, max_n)
            order = jnp.argsort(1.0 - mask_row[perm], stable=True)
            perm = perm[order]

            def step_body(carry2, step):
                g_vars, d_vars, g_os, d_os, sums = carry2
                take = jax.lax.dynamic_slice_in_dim(
                    perm, step * batch_size, batch_size
                )
                b_idx = idx_row[take]
                w_b = mask_row[take]
                x_b = jnp.take(x, b_idx, axis=0)
                y_b = jnp.take(y, b_idx, axis=0)
                skey = jax.random.fold_in(ekey, step)
                kz, kl, kg, kd_ = jax.random.split(skey, 4)

                z = gen.sample_noise(kz, batch_size)
                gen_labels = gen.sample_labels(kl, batch_size)

                # --- G step (ac_gan_model_trainer.py:80-97) ---
                g_params = g_vars["params"]
                g_static = {k: v for k, v in g_vars.items() if k != "params"}
                (g_loss, (new_g_vars, fakes)), g_grads = g_grad(
                    g_params, g_static, d_vars, z, gen_labels, w_b, kg
                )
                g_updates, new_g_os = g_opt.update(g_grads, g_os, g_params)
                new_g_params = optax.apply_updates(g_params, g_updates)
                new_g_vars = {**new_g_vars, "params": new_g_params}

                # --- D step on detached fakes (:99-116) ---
                d_params = d_vars["params"]
                d_static = {k: v for k, v in d_vars.items() if k != "params"}
                (d_loss, new_d_vars), d_grads = d_grad(
                    d_params, d_static, jax.lax.stop_gradient(fakes),
                    gen_labels, x_b, y_b, w_b, kd_,
                )
                d_updates, new_d_os = d_opt.update(d_grads, d_os, d_params)
                new_d_vars = {
                    **new_d_vars,
                    "params": optax.apply_updates(d_params, d_updates),
                }

                # fully-padded batch -> strict no-op
                valid = jnp.sum(w_b) > 0
                sel = lambda n, o: jax.tree.map(
                    lambda a, b: jnp.where(valid, a, b), n, o
                )
                out = (
                    sel(new_g_vars, g_vars),
                    sel(new_d_vars, d_vars),
                    sel(new_g_os, g_os),
                    sel(new_d_os, d_os),
                    {
                        "g_loss_sum": sums["g_loss_sum"]
                        + jnp.where(valid, g_loss, 0.0),
                        "d_loss_sum": sums["d_loss_sum"]
                        + jnp.where(valid, d_loss, 0.0),
                        "batches": sums["batches"]
                        + jnp.where(valid, 1.0, 0.0),
                    },
                )
                return out

            n_steps = dynamic_trip_count(
                mask_row, batch_size, steps_per_epoch
            )
            carry = jax.lax.fori_loop(
                0, n_steps, lambda i, c: step_body(c, i),
                (g_vars, d_vars, g_os, d_os, sums),
            )
            return carry, None

        sums0 = {
            "g_loss_sum": jnp.asarray(0.0),
            "d_loss_sum": jnp.asarray(0.0),
            "batches": jnp.asarray(0.0),
        }
        g_os = g_opt.init(gen_vars["params"])
        d_os = d_opt.init(disc_vars["params"])
        ekeys = jax.vmap(lambda e: jax.random.fold_in(rng, e))(
            jnp.arange(train_cfg.epochs)
        )
        (g_vars, d_vars, _, _, sums), _ = jax.lax.scan(
            epoch_body, (gen_vars, disc_vars, g_os, d_os, sums0), ekeys
        )
        n_k = jnp.sum(mask_row)
        return g_vars, d_vars, n_k, sums

    return update


# ---------------------------------------------------------------------------
# Synthetic-set generation, logit extraction, distillation
# ---------------------------------------------------------------------------


def build_dataset_generator(gen: GanModel, size: int, batch_size: int):
    """``generate(gen_vars, rng)`` -> (synth_x [S,H,W,C], labels [S]).

    Balanced labels + batched eval-mode generation (reference
    ``generate_fake_dataset``, ``fedgdkd/server.py:196-206``). ``size`` must
    be a multiple of ``batch_size`` (static shapes under jit).
    """
    assert size % batch_size == 0, (size, batch_size)
    n_batches = size // batch_size
    labels = (
        jnp.arange(size, dtype=jnp.int32) % max(gen.num_classes, 1)
        if gen.conditional
        else None
    )

    def generate(gen_vars, rng):
        def body(_, i):
            z = gen.sample_noise(jax.random.fold_in(rng, i), batch_size)
            lb = (
                jax.lax.dynamic_slice_in_dim(labels, i * batch_size, batch_size)
                if labels is not None
                else None
            )
            return None, gen.apply_eval(gen_vars, z, lb)

        _, batches = jax.lax.scan(body, None, jnp.arange(n_batches))
        synth = batches.reshape((size,) + batches.shape[2:])
        return synth, (labels if labels is not None
                       else jnp.zeros((size,), jnp.int32))

    return generate


def build_logit_extractor(disc: DiscHandle, size: int, batch_size: int):
    """``logits(disc_vars, synth_x)`` -> [S, K], eval mode (reference
    ``get_classifier_logits``, ``fedgdkd/model_trainer.py:115-136``)."""
    assert size % batch_size == 0
    n_batches = size // batch_size

    def extract(disc_vars, synth_x):
        def body(_, i):
            xb = jax.lax.dynamic_slice_in_dim(
                synth_x, i * batch_size, batch_size
            )
            return None, disc.apply_eval(disc_vars, xb)

        _, out = jax.lax.scan(body, None, jnp.arange(n_batches))
        return out.reshape((size, -1))

    return extract


def build_kd_update(
    disc: DiscHandle,
    train_cfg: TrainConfig,
    gan_cfg: GanConfig,
    size: int,
    batch_size: int,
):
    """``kd(disc_vars, synth_x, labels, teacher_logits, rng)`` -> new vars.

    The classifier-side distillation loop (reference
    ``knowledge_distillation``, ``fedgdkd/model_trainer.py:138-177``):
    ``kd_epochs`` passes of ``(1-kd_alpha)*CE + kd_alpha*SoftTarget(T)``.
    """
    assert size % batch_size == 0
    n_batches = size // batch_size
    opt = make_client_optimizer(train_cfg)

    def loss_fn(params, static, xb, yb, tb, rng):
        variables = {**static, "params": params}
        logits, new_vars = disc.apply_train(variables, xb, rng)
        kd_loss = KD.soft_target(logits, tb, gan_cfg.kd_temperature)
        ce = jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, yb)
        )
        loss = (1 - gan_cfg.kd_alpha) * ce + gan_cfg.kd_alpha * kd_loss
        return loss, (new_vars, kd_loss)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def kd(disc_vars, synth_x, labels, teacher_logits, rng):
        opt_state = opt.init(disc_vars["params"])

        def epoch_body(carry, ekey):
            variables, opt_state, losses = carry

            def step_body(carry2, i):
                variables, opt_state, losses = carry2
                sl = lambda a: jax.lax.dynamic_slice_in_dim(
                    a, i * batch_size, batch_size
                )
                params = variables["params"]
                static = {k: v for k, v in variables.items() if k != "params"}
                (loss, (new_vars, kd_l)), grads = grad_fn(
                    params, static, sl(synth_x), sl(labels),
                    sl(teacher_logits), jax.random.fold_in(ekey, i),
                )
                updates, new_os = opt.update(grads, opt_state, params)
                new_vars = {
                    **new_vars,
                    "params": optax.apply_updates(params, updates),
                }
                losses = {
                    "kd_loss_sum": losses["kd_loss_sum"] + kd_l,
                    "dist_loss_sum": losses["dist_loss_sum"] + loss,
                    "batches": losses["batches"] + 1.0,
                }
                return (new_vars, new_os, losses), None

            carry, _ = jax.lax.scan(
                step_body, (variables, opt_state, losses),
                jnp.arange(n_batches),
            )
            return carry, None

        losses0 = {
            "kd_loss_sum": jnp.asarray(0.0),
            "dist_loss_sum": jnp.asarray(0.0),
            "batches": jnp.asarray(0.0),
        }
        ekeys = jax.vmap(lambda e: jax.random.fold_in(rng, e))(
            jnp.arange(gan_cfg.kd_epochs)
        )
        (variables, _, losses), _ = jax.lax.scan(
            epoch_body, (disc_vars, opt_state, losses0), ekeys
        )
        return variables, losses

    return kd


def build_cohort_gan_update(
    gen: GanModel,
    classifier,  # FedModel with supports_cohort() — the ssgan "D"
    train_cfg: TrainConfig,
    gan_cfg: GanConfig,
    batch_size: int,
    max_n: int,
    cohort: int,
):
    """Cohort-fused :func:`build_gan_local_update` (ssgan mode): the
    whole sub-cohort's adversarial phase runs as grouped networks — the
    generator pyramid via :meth:`GanModel.apply_cohort_train`, the
    classifier via :meth:`FedModel.apply_cohort_train` — instead of
    ``vmap`` over per-client nets (batched-kernel convs + per-op layout
    transposes, the lowering the cohort machinery exists to avoid).

    Same contract as ``vmap(build_gan_local_update(...), in_axes=(None,
    0, 0, 0, None, None, 0))``: ``update(gen_vars_global, cls_stacked,
    idx_rows [C, max_n], mask_rows, x, y, rngs [C])`` returns
    ``(g_stacked, cls_stacked, n_k [C], loss sums with [C] leaves)``,
    with the SAME per-step RNG derivation per client (z / fake-label
    draws are bitwise the vmapped path's). Per-client losses are summed
    so ``d(total)/d(params_c)`` is exactly client c's gradient; a
    fully-padded batch is where-gated per client (params, optimizer
    state — including the per-client adam step count of
    :func:`make_stacked_gen_optimizer` — and generator BN stats), so
    padded steps remain strict no-ops. The step loop's trip count is
    the SUB-COHORT's max ceil(n_k/B) (dynamic), which is what makes
    ``stack_utils.size_grouped_lanes`` effective on top."""
    assert max_n % batch_size == 0
    steps_per_epoch = max_n // batch_size
    C = cohort
    g_opt = make_stacked_gen_optimizer(gan_cfg)
    d_opt = make_client_optimizer(train_cfg)

    def g_loss_fn(g_params, g_static, d_vars, z, gen_labels, w_rows):
        g_vars = {**g_static, "params": g_params}
        fakes, new_g_vars = gen.apply_cohort_train(g_vars, z, gen_labels)
        out, _ = classifier.apply_cohort_train(
            d_vars, fakes, jax.random.key(0)
        )
        per = jax.vmap(generator_loss_ssgan)(out, gen_labels, w_rows)
        return jnp.sum(per), (new_g_vars, fakes, per)

    def d_loss_fn(d_params, d_static, fakes, gen_labels, x_cb, y_cb,
                  w_rows):
        d_vars = {**d_static, "params": d_params}
        cls_fake, d1 = classifier.apply_cohort_train(
            d_vars, fakes, jax.random.key(0)
        )
        cls_real, d2 = classifier.apply_cohort_train(
            d1, x_cb, jax.random.key(0)
        )
        per = jax.vmap(discriminator_loss_ssgan)(
            cls_fake, gen_labels, cls_real, y_cb, w_rows
        )
        return jnp.sum(per), (d2, per)

    g_grad = jax.value_and_grad(g_loss_fn, has_aux=True)
    d_grad = jax.value_and_grad(d_loss_fn, has_aux=True)

    def update(gen_vars, cls_vars, idx_rows, mask_rows, x, y, rngs):
        g_vars0 = jax.tree.map(
            lambda v: jnp.broadcast_to(v[None], (C,) + v.shape), gen_vars
        )

        def epoch_body(carry, ekeys):
            g_vars, d_vars, g_os, d_os, sums = carry
            # per-client valid-first perms, bitwise the vmapped path's
            def mk_perm(ek, mrow):
                p = jax.random.permutation(ek, max_n)
                return p[jnp.argsort(1.0 - mrow[p], stable=True)]

            perms = jax.vmap(mk_perm)(ekeys, mask_rows)

            def step_body(step, carry2):
                g_vars, d_vars, g_os, d_os, sums = carry2
                take = jax.lax.dynamic_slice_in_dim(
                    perms, step * batch_size, batch_size, axis=1
                )  # [C, B]
                b_idx = jnp.take_along_axis(idx_rows, take, axis=1)
                w_b = jnp.take_along_axis(mask_rows, take, axis=1)
                x_cb = jnp.take(x, b_idx.reshape(-1), axis=0).reshape(
                    (C, batch_size) + x.shape[1:]
                )
                y_cb = jnp.take(y, b_idx.reshape(-1), axis=0).reshape(
                    (C, batch_size)
                )
                skeys = jax.vmap(
                    lambda ek: jax.random.fold_in(ek, step)
                )(ekeys)
                ks = jax.vmap(lambda k: jax.random.split(k, 4))(skeys)
                z = jax.vmap(
                    lambda k: gen.sample_noise(k, batch_size)
                )(ks[:, 0])
                gen_labels = jax.vmap(
                    lambda k: gen.sample_labels(k, batch_size)
                )(ks[:, 1])

                g_params = g_vars["params"]
                g_static = {
                    k: v for k, v in g_vars.items() if k != "params"
                }
                (_, (new_g_vars, fakes, g_per)), g_grads = g_grad(
                    g_params, g_static, d_vars, z, gen_labels, w_b
                )
                g_updates, new_g_os = g_opt.update(
                    g_grads, g_os, g_params
                )
                new_g_vars = {
                    **new_g_vars,
                    "params": optax.apply_updates(g_params, g_updates),
                }

                d_params = d_vars["params"]
                d_static = {
                    k: v for k, v in d_vars.items() if k != "params"
                }
                (_, (new_d_vars, d_per)), d_grads = d_grad(
                    d_params, d_static, jax.lax.stop_gradient(fakes),
                    gen_labels, x_cb, y_cb, w_b,
                )
                d_updates, new_d_os = d_opt.update(
                    d_grads, d_os, d_params
                )
                new_d_vars = {
                    **new_d_vars,
                    "params": optax.apply_updates(d_params, d_updates),
                }

                valid = jnp.sum(w_b, axis=1) > 0  # [C]

                def sel(new, old):
                    return jax.tree.map(
                        lambda a, b: jnp.where(
                            valid.reshape((C,) + (1,) * (a.ndim - 1)),
                            a, b,
                        ),
                        new, old,
                    )

                sums = {
                    "g_loss_sum": sums["g_loss_sum"]
                    + jnp.where(valid, g_per, 0.0),
                    "d_loss_sum": sums["d_loss_sum"]
                    + jnp.where(valid, d_per, 0.0),
                    "batches": sums["batches"]
                    + jnp.where(valid, 1.0, 0.0),
                }
                return (
                    sel(new_g_vars, g_vars), sel(new_d_vars, d_vars),
                    sel(new_g_os, g_os), sel(new_d_os, d_os), sums,
                )

            n_steps = jnp.max(
                jax.vmap(
                    lambda m: dynamic_trip_count(
                        m, batch_size, steps_per_epoch
                    )
                )(mask_rows)
            )
            carry = jax.lax.fori_loop(
                0, n_steps, step_body,
                (g_vars, d_vars, g_os, d_os, sums),
            )
            return carry, None

        sums0 = {
            "g_loss_sum": jnp.zeros((C,)),
            "d_loss_sum": jnp.zeros((C,)),
            "batches": jnp.zeros((C,)),
        }
        g_os = g_opt.init(g_vars0["params"])
        d_os = d_opt.init(cls_vars["params"])
        ekeys = jax.vmap(
            lambda e: jax.vmap(
                lambda r: jax.random.fold_in(r, e)
            )(rngs)
        )(jnp.arange(train_cfg.epochs))  # [E, C]
        (g_vars, d_vars, _, _, sums), _ = jax.lax.scan(
            epoch_body, (g_vars0, cls_vars, g_os, d_os, sums0), ekeys
        )
        n_k = jnp.sum(mask_rows, axis=1)
        return g_vars, d_vars, n_k, sums

    return update


def build_cohort_kd_update(
    model,  # FedModel with supports_cohort() — the classifier itself
    train_cfg: TrainConfig,
    gan_cfg: GanConfig,
    size: int,
    batch_size: int,
    cohort: int,
):
    """Cohort-fused :func:`build_kd_update`: every client's KD pass runs
    inside ONE cohort-grouped network application per batch instead of
    ``vmap`` over per-client classifiers (whose batched-kernel convs
    lower poorly on TPU — the same motivation as
    ``base.build_cohort_local_update``). All clients distill on the SAME
    synthetic batches, only the leave-one-out teacher differs per
    client, so the input is a broadcast and per-client losses sum so
    that ``d(total)/d(params_c)`` is exactly client c's gradient.

    Same contract as ``vmap(build_kd_update(...), in_axes=(0, None,
    None, 0, 0))``: ``kd(stacked_vars, synth_x, labels, teachers [C,S,K],
    rngs [C])`` -> (stacked vars, loss sums with [C] leaves). Eligible
    only for dropout-free classifiers with per-client-stackable
    optimizer state (``base.cohort_update_supported``) — dropout would
    draw one mask over the widened activations, and the per-client rng
    streams (dropout-only) would differ from the vmapped path."""
    assert size % batch_size == 0
    n_batches = size // batch_size
    C = cohort
    opt = make_client_optimizer(train_cfg)

    def loss_fn(stacked_params, static_stacked, xb, yb, tb, rng):
        variables = {**static_stacked, "params": stacked_params}
        x_cb = jnp.broadcast_to(xb[None], (C,) + xb.shape)
        logits, new_vars = model.apply_cohort_train(variables, x_cb, rng)
        kd_l = jax.vmap(
            lambda s, t: KD.soft_target(s, t, gan_cfg.kd_temperature)
        )(logits, tb)  # [C]
        ce = jax.vmap(
            lambda lg: jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(lg, yb)
            )
        )(logits)
        per_client = (1 - gan_cfg.kd_alpha) * ce + gan_cfg.kd_alpha * kd_l
        return jnp.sum(per_client), (new_vars, per_client, kd_l)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def kd(stacked_vars, synth_x, labels, teachers, rngs):
        opt_state = opt.init(stacked_vars["params"])
        # rng feeds dropout only, which cohort support excludes; one
        # representative key keeps the signature uniform
        rng0 = rngs[0]

        def epoch_body(carry, ekey):
            variables, opt_state, losses = carry

            def step_body(carry2, i):
                variables, opt_state, losses = carry2
                sl = lambda a: jax.lax.dynamic_slice_in_dim(
                    a, i * batch_size, batch_size
                )
                tb = jax.vmap(
                    lambda t: jax.lax.dynamic_slice_in_dim(
                        t, i * batch_size, batch_size
                    )
                )(teachers)
                params = variables["params"]
                static = {
                    k: v for k, v in variables.items() if k != "params"
                }
                (_, (new_vars, dist_l, kd_l)), grads = grad_fn(
                    params, static, sl(synth_x), sl(labels), tb,
                    jax.random.fold_in(ekey, i),
                )
                updates, new_os = opt.update(grads, opt_state, params)
                new_vars = {
                    **new_vars,
                    "params": optax.apply_updates(params, updates),
                }
                losses = {
                    "kd_loss_sum": losses["kd_loss_sum"] + kd_l,
                    "dist_loss_sum": losses["dist_loss_sum"] + dist_l,
                    "batches": losses["batches"] + 1.0,
                }
                return (new_vars, new_os, losses), None

            carry, _ = jax.lax.scan(
                step_body, (variables, opt_state, losses),
                jnp.arange(n_batches),
            )
            return carry, None

        losses0 = {
            "kd_loss_sum": jnp.zeros((C,)),
            "dist_loss_sum": jnp.zeros((C,)),
            "batches": jnp.zeros((C,)),
        }
        ekeys = jax.vmap(lambda e: jax.random.fold_in(rng0, e))(
            jnp.arange(gan_cfg.kd_epochs)
        )
        (variables, _, losses), _ = jax.lax.scan(
            epoch_body, (stacked_vars, opt_state, losses0), ekeys
        )
        return variables, losses

    return kd

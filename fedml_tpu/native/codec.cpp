// Tensor-frame codec: the native serialization hot path for the
// cross-host transport layer.
//
// The reference reaches native speed through pip-packaged bindings
// (mpi4py's C MPI, grpcio's C-core — SURVEY.md L0); its own payload path
// is python pickle of whole state_dicts (mpi_send_thread.py:22-27). This
// codec replaces that for bulk tensors: a frame is
//
//   [u64 total_len][u32 n_tensors]
//   n x [u32 dtype_code][u32 ndim][u64 dims...][u64 nbytes]
//   concatenated raw tensor bytes (8-byte aligned)
//
// pack() gathers all tensor buffers into one contiguous frame with
// multi-threaded memcpy (model blobs are 100MB-1GB class — memory
// bandwidth bound, so threads help); crc32c-style checksum guards DCN
// frames. unpack offsets let python build zero-copy numpy views.
//
// Built with: g++ -O3 -march=native -shared -fPIC -pthread

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Simple CRC32 (polynomial 0xEDB88320), table-driven.
static uint32_t crc_table[256];
static bool crc_init_done = false;

static void crc_init() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++)
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  crc_init_done = true;
}

uint32_t fedml_crc32(const uint8_t* buf, uint64_t len) {
  if (!crc_init_done) crc_init();
  uint32_t c = 0xFFFFFFFFu;
  for (uint64_t i = 0; i < len; i++)
    c = crc_table[(c ^ buf[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// Header sizing: python computes the header; C++ does the bulk copy.
// copy_gather: memcpy n_srcs buffers into dst at given offsets, using up
// to n_threads worker threads split by bytes.
void fedml_copy_gather(uint8_t* dst, const uint8_t** srcs,
                       const uint64_t* sizes, const uint64_t* offsets,
                       uint32_t n_srcs, uint32_t n_threads) {
  if (n_threads <= 1) {
    for (uint32_t i = 0; i < n_srcs; i++)
      std::memcpy(dst + offsets[i], srcs[i], sizes[i]);
    return;
  }
  // assign tensors to threads round-robin weighted by bytes
  std::vector<std::vector<uint32_t>> buckets(n_threads);
  std::vector<uint64_t> loads(n_threads, 0);
  for (uint32_t i = 0; i < n_srcs; i++) {
    uint32_t t = 0;
    for (uint32_t j = 1; j < n_threads; j++)
      if (loads[j] < loads[t]) t = j;
    buckets[t].push_back(i);
    loads[t] += sizes[i];
  }
  std::vector<std::thread> workers;
  for (uint32_t t = 0; t < n_threads; t++) {
    if (buckets[t].empty()) continue;
    workers.emplace_back([&, t]() {
      for (uint32_t i : buckets[t])
        std::memcpy(dst + offsets[i], srcs[i], sizes[i]);
    });
  }
  for (auto& w : workers) w.join();
}

// scatter: the inverse — copy slices of one frame out to n dst buffers.
void fedml_copy_scatter(const uint8_t* src, uint8_t** dsts,
                        const uint64_t* sizes, const uint64_t* offsets,
                        uint32_t n_dsts, uint32_t n_threads) {
  if (n_threads <= 1) {
    for (uint32_t i = 0; i < n_dsts; i++)
      std::memcpy(dsts[i], src + offsets[i], sizes[i]);
    return;
  }
  std::vector<std::vector<uint32_t>> buckets(n_threads);
  std::vector<uint64_t> loads(n_threads, 0);
  for (uint32_t i = 0; i < n_dsts; i++) {
    uint32_t t = 0;
    for (uint32_t j = 1; j < n_threads; j++)
      if (loads[j] < loads[t]) t = j;
    buckets[t].push_back(i);
    loads[t] += sizes[i];
  }
  std::vector<std::thread> workers;
  for (uint32_t t = 0; t < n_threads; t++) {
    if (buckets[t].empty()) continue;
    workers.emplace_back([&, t]() {
      for (uint32_t i : buckets[t])
        std::memcpy(dsts[i], src + offsets[i], sizes[i]);
    });
  }
  for (auto& w : workers) w.join();
}

// Quantize float32 -> uint8 with per-tensor scale/zero (transport
// compression for model blobs; lossy, opt-in).
void fedml_quantize_u8(const float* src, uint8_t* dst, uint64_t n,
                       float lo, float hi) {
  float scale = (hi > lo) ? 255.0f / (hi - lo) : 0.0f;
  for (uint64_t i = 0; i < n; i++) {
    float v = (src[i] - lo) * scale;
    if (v < 0.0f) v = 0.0f;
    if (v > 255.0f) v = 255.0f;
    dst[i] = (uint8_t)(v + 0.5f);
  }
}

void fedml_dequantize_u8(const uint8_t* src, float* dst, uint64_t n,
                         float lo, float hi) {
  float scale = (hi - lo) / 255.0f;
  for (uint64_t i = 0; i < n; i++) dst[i] = lo + src[i] * scale;
}

}  // extern "C"

"""Python binding for the C++ tensor-frame codec (``codec.cpp``).

Builds the shared library on first use with g++ (cached next to the
source; no pybind11 — plain ctypes over an ``extern "C"`` surface). Falls
back to pure-numpy implementations when no compiler is available, so the
transport layer never hard-depends on the native build.

Frame layout (see codec.cpp): little-endian header describing each tensor
(dtype, shape, nbytes) followed by 8-byte-aligned raw buffers. ``unpack``
returns zero-copy numpy views into the frame.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "codec.cpp")
_LIB = os.path.join(_HERE, "_codec.so")
_lock = threading.Lock()
_lib = None
_tried = False

_DTYPES = [
    np.dtype("float32"), np.dtype("float64"), np.dtype("int32"),
    np.dtype("int64"), np.dtype("uint8"), np.dtype("bool"),
    np.dtype("float16"), np.dtype("int8"), np.dtype("uint16"),
    np.dtype("uint32"), np.dtype("uint64"), np.dtype("int16"),
]
_DTYPE_CODE = {d: i for i, d in enumerate(_DTYPES)}


def codec_supports(dtype) -> bool:
    """Whether the frame codec can carry this dtype (bf16/complex/object
    arrays must stay on the pickle path)."""
    try:
        return np.dtype(dtype) in _DTYPE_CODE
    except TypeError:
        return False


def _build() -> str | None:
    if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(
        _SRC
    ):
        return _LIB
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
             _SRC, "-o", _LIB],
            check=True, capture_output=True, timeout=120,
        )
        return _LIB
    except (OSError, subprocess.SubprocessError):
        return None


def _load():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        path = _build()
        if path is None:
            return None
        lib = ctypes.CDLL(path)
        lib.fedml_crc32.restype = ctypes.c_uint32
        lib.fedml_crc32.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        PTRS = ctypes.POINTER(ctypes.c_void_p)
        U64S = ctypes.POINTER(ctypes.c_uint64)
        lib.fedml_copy_gather.argtypes = [
            ctypes.c_void_p, PTRS, U64S, U64S, ctypes.c_uint32,
            ctypes.c_uint32,
        ]
        lib.fedml_copy_scatter.argtypes = [
            ctypes.c_void_p, PTRS, U64S, U64S, ctypes.c_uint32,
            ctypes.c_uint32,
        ]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def crc32(buf: bytes) -> int:
    lib = _load()
    if lib is None:
        import zlib

        return zlib.crc32(buf) & 0xFFFFFFFF
    return int(lib.fedml_crc32(buf, len(buf)))


_MAGIC = b"FTC1"


class TensorCodec:
    """Pack/unpack a flat list of numpy arrays into one contiguous frame."""

    def __init__(self, n_threads: int = 4):
        self.n_threads = n_threads

    # -- pack ---------------------------------------------------------------
    def pack(self, arrays: list[np.ndarray]) -> bytes:
        arrays = [np.ascontiguousarray(a) for a in arrays]
        header = bytearray()
        header += _MAGIC
        header += struct.pack("<I", len(arrays))
        offsets, sizes = [], []
        # compute payload offsets (8-byte aligned) after the header
        for a in arrays:
            code = _DTYPE_CODE[a.dtype]
            header += struct.pack("<II", code, a.ndim)
            header += struct.pack(f"<{a.ndim}q", *a.shape)
            header += struct.pack("<Q", a.nbytes)
        base = (len(header) + 8 + 7) & ~7  # + u64 payload start marker
        header += struct.pack("<Q", base)
        cur = base
        for a in arrays:
            offsets.append(cur)
            sizes.append(a.nbytes)
            cur = (cur + a.nbytes + 7) & ~7
        frame = bytearray(cur)
        frame[: len(header)] = header

        lib = _load()
        if lib is None or not arrays:
            for a, off in zip(arrays, offsets):
                frame[off:off + a.nbytes] = a.tobytes()
            return bytes(frame)

        n = len(arrays)
        src_ptrs = (ctypes.c_void_p * n)(
            *[a.ctypes.data for a in arrays]
        )
        size_arr = (ctypes.c_uint64 * n)(*sizes)
        off_arr = (ctypes.c_uint64 * n)(*offsets)
        dst = (ctypes.c_char * len(frame)).from_buffer(frame)
        lib.fedml_copy_gather(
            ctypes.addressof(dst),
            ctypes.cast(src_ptrs, ctypes.POINTER(ctypes.c_void_p)),
            size_arr, off_arr, n, self.n_threads,
        )
        return bytes(frame)

    # -- unpack -------------------------------------------------------------
    def unpack(self, frame: bytes) -> list[np.ndarray]:
        assert frame[:4] == _MAGIC, "bad tensor frame"
        view = memoryview(frame)
        pos = 4
        (n,) = struct.unpack_from("<I", view, pos)
        pos += 4
        metas = []
        for _ in range(n):
            code, ndim = struct.unpack_from("<II", view, pos)
            pos += 8
            shape = struct.unpack_from(f"<{ndim}q", view, pos)
            pos += 8 * ndim
            (nbytes,) = struct.unpack_from("<Q", view, pos)
            pos += 8
            metas.append((_DTYPES[code], shape, nbytes))
        (base,) = struct.unpack_from("<Q", view, pos)
        out = []
        cur = base
        for dtype, shape, nbytes in metas:
            arr = np.frombuffer(
                view[cur:cur + nbytes], dtype=dtype
            ).reshape(shape)
            out.append(arr)
            cur = (cur + nbytes + 7) & ~7
        return out

"""Native (C++) runtime components, loaded via ctypes."""

from fedml_tpu.native.codec import (  # noqa: F401
    TensorCodec,
    crc32,
    native_available,
)

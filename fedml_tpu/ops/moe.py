"""Mixture-of-Experts FFN with expert parallelism (the "ep" mesh axis).

Beyond-reference capability (the reference has no MoE; SURVEY.md §2.7
lists EP as absent). TPU-native design: experts live as a stacked
parameter pytree ``[E, ...]`` sharded over the ``ep`` axis; tokens are
routed top-1 and exchanged with ``jax.lax.all_to_all`` — the canonical
expert-parallel pattern (tokens sorted into per-destination-shard
capacity-padded buckets, one all_to_all out, expert compute, one
all_to_all back, unsort).

Static shapes throughout: each (source shard -> destination shard) lane
carries a fixed ``capacity`` of token slots; overflow tokens are dropped
(standard MoE capacity semantics) and masked slots contribute zero.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def init_moe_params(key, num_experts: int, d_model: int, d_hidden: int):
    """Router + stacked expert FFNs ([E, ...] leaves)."""
    k1, k2, k3 = jax.random.split(key, 3)
    s1 = 1.0 / jnp.sqrt(d_model)
    s2 = 1.0 / jnp.sqrt(d_hidden)
    return {
        "router": jax.random.normal(k1, (d_model, num_experts)) * s1,
        "w_in": jax.random.normal(k2, (num_experts, d_model, d_hidden)) * s1,
        "w_out": jax.random.normal(k3, (num_experts, d_hidden, d_model)) * s2,
    }


def _expert_ffn(w_in, w_out, x):
    return jax.nn.gelu(x @ w_in) @ w_out


def moe_ffn_reference(params, x):
    """Single-device top-1 MoE (the oracle): every token goes to its
    argmax expert, scaled by the softmax gate weight."""
    logits = x @ params["router"]
    gates = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(logits, axis=-1)  # [n]
    gate = jnp.take_along_axis(gates, expert[:, None], axis=1)[:, 0]
    outs = jax.vmap(
        lambda wi, wo: _expert_ffn(wi, wo, x)
    )(params["w_in"], params["w_out"])  # [E, n, d]
    sel = outs[expert, jnp.arange(x.shape[0])]
    return sel * gate[:, None]


def make_expert_parallel_moe(mesh, axis_name: str = "ep",
                             capacity_factor: float = 2.0):
    """Build ``moe(params, x) -> y`` running under ``shard_map``:
    ``params['w_in']/['w_out']`` sharded over experts on ``axis_name``,
    tokens sharded over the same axis, routed cross-shard via all_to_all.

    Call with GLOBAL arrays; returns the sharded computation wrapped and
    ready (in/out specs applied)."""
    from fedml_tpu.core.compat import shard_map
    from jax.sharding import PartitionSpec as P

    p = mesh.shape[axis_name]

    def local_moe(router, w_in, w_out, x):
        # x: [n_local, d]; w_in/w_out: [E/p, ...] local experts
        n_local, d = x.shape
        e_local = w_in.shape[0]
        num_experts = e_local * p
        shard = jax.lax.axis_index(axis_name)
        capacity = int(capacity_factor * n_local / p) or 1

        logits = x @ router
        gates = jax.nn.softmax(logits, axis=-1)
        expert = jnp.argmax(logits, axis=-1)  # global expert id [n_local]
        gate = jnp.take_along_axis(gates, expert[:, None], axis=1)[:, 0]
        dest = expert // e_local  # destination shard per token

        # slot each token into its destination bucket (capacity-limited):
        # position = rank of the token among same-destination tokens
        order = jnp.argsort(dest)  # stable: groups by destination
        ranks = jnp.zeros((n_local,), jnp.int32)
        # rank within destination group = index - first index of the group
        sorted_dest = dest[order]
        first_idx = jnp.searchsorted(sorted_dest, jnp.arange(p))
        pos_sorted = jnp.arange(n_local) - first_idx[sorted_dest]
        ranks = ranks.at[order].set(pos_sorted.astype(jnp.int32))
        keep = ranks < capacity

        # scatter tokens into [p, capacity, d] send buffer (+gates, +ids)
        buf_x = jnp.zeros((p, capacity, d), x.dtype)
        buf_e = jnp.full((p, capacity), -1, jnp.int32)  # -1 = empty slot
        slot_dest = jnp.where(keep, dest, p - 1)
        slot_rank = jnp.where(keep, ranks, capacity - 1)
        # masked scatter: dropped tokens write zeros/-1 via the mask trick
        buf_x = buf_x.at[slot_dest, slot_rank].add(
            jnp.where(keep[:, None], x, 0.0)
        )
        buf_e = buf_e.at[slot_dest, slot_rank].max(
            jnp.where(keep, expert, -1)
        )

        # exchange: [p, capacity, d] -> tokens FROM every shard
        recv_x = jax.lax.all_to_all(
            buf_x, axis_name, split_axis=0, concat_axis=0, tiled=False
        )  # [p, capacity, d]
        recv_e = jax.lax.all_to_all(
            buf_e, axis_name, split_axis=0, concat_axis=0, tiled=False
        )

        # local expert compute on received tokens
        flat_x = recv_x.reshape(p * capacity, d)
        flat_e = recv_e.reshape(p * capacity)
        local_e = flat_e - shard * e_local  # local expert index
        valid = flat_e >= 0
        local_e = jnp.clip(local_e, 0, e_local - 1)
        outs = jax.vmap(
            lambda wi, wo: _expert_ffn(wi, wo, flat_x)
        )(w_in, w_out)  # [E/p, p*capacity, d]
        y = outs[local_e, jnp.arange(p * capacity)]
        y = jnp.where(valid[:, None], y, 0.0)

        # return trip + unscatter
        back = jax.lax.all_to_all(
            y.reshape(p, capacity, d), axis_name,
            split_axis=0, concat_axis=0, tiled=False,
        )  # [p, capacity, d] keyed by original (dest, rank)
        gathered = back[slot_dest, slot_rank]
        gathered = jnp.where(keep[:, None], gathered, 0.0)
        return gathered * gate[:, None]

    spec_x = P(axis_name)
    spec_e = P(axis_name)
    return shard_map(
        local_moe,
        mesh=mesh,
        in_specs=(P(), spec_e, spec_e, spec_x),
        out_specs=spec_x,
        check_vma=False,
    )

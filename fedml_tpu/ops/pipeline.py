"""Pipeline parallelism: GPipe-style microbatch schedule over a "pp" axis.

Beyond-reference capability (SURVEY.md §2.7: the reference's closest
analog is SplitNN's round-robin ring). Each shard of the ``pp`` mesh axis
owns ONE stage's parameters; microbatches stream through the pipeline
with activations hopping stage->stage via ``jax.lax.ppermute`` each tick.
The schedule runs ``M + p - 1`` ticks for ``M`` microbatches over ``p``
stages (fill + drain); every tensor shape is static.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def make_pipeline(stage_fn, mesh, axis_name: str = "pp"):
    """Build ``pipeline(stage_params, x) -> y``:

    - ``stage_params``: pytree with a leading stage axis [p, ...], sharded
      over ``axis_name`` (each shard holds its own stage's params).
    - ``x``: [M, mb, ...] microbatches (replicated).
    - ``stage_fn(params, x_mb) -> y_mb``: one stage's computation (shapes
      preserved across stages).

    Returns y [M, mb, ...] (replicated; produced on the last stage and
    psum-broadcast)."""
    from fedml_tpu.core.compat import shard_map
    from jax.sharding import PartitionSpec as P

    p = mesh.shape[axis_name]
    # tuple, not list: `run` below closes over this and is compiled by
    # shard_map — a mutable closure is invisible to jit's cache key
    # (fedlint recompile-hazard)
    perm = tuple((i, (i + 1) % p) for i in range(p))

    def run(stage_params, x):
        # stage_params arrives [1, ...] on each shard; drop the stage axis
        local_params = jax.tree.map(lambda l: l[0], stage_params)
        shard = jax.lax.axis_index(axis_name)
        m = x.shape[0]
        mb_shape = x.shape[1:]
        ticks = m + p - 1

        def tick(carry, t):
            state, outputs = carry
            # stage 0 injects microbatch t (zeros once drained)
            inject = jnp.where(
                t < m,
                jax.lax.dynamic_index_in_dim(
                    x, jnp.clip(t, 0, m - 1), keepdims=False
                ),
                jnp.zeros(mb_shape, x.dtype),
            )
            inp = jnp.where(shard == 0, inject, state)
            out = stage_fn(local_params, inp)
            # last stage emits microbatch t-(p-1) at tick t
            m_idx = t - (p - 1)
            emit = (shard == p - 1) & (m_idx >= 0)
            safe = jnp.clip(m_idx, 0, m - 1)
            cur = jax.lax.dynamic_index_in_dim(outputs, safe,
                                               keepdims=False)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(emit, out, cur), safe, axis=0
            )
            state = jax.lax.ppermute(out, axis_name, perm)
            return (state, outputs), None

        init = (
            jnp.zeros(mb_shape, x.dtype),
            jnp.zeros((m,) + mb_shape, x.dtype),
        )
        (_, outputs), _ = jax.lax.scan(tick, init, jnp.arange(ticks))
        # outputs are only populated on the last stage; broadcast them
        outputs = jnp.where(shard == p - 1, outputs, 0.0)
        return jax.lax.psum(outputs, axis_name)

    return shard_map(
        run,
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
        check_vma=False,
    )

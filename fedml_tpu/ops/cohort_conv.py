"""Cohort-grouped convolution: fast ``vmap`` over per-client kernels.

The FL hot loop vmaps client local SGD over the cohort, so every conv runs
with a *batched kernel* (one kernel per client). XLA's stock lowering for
batched-kernel convolutions on TPU is poor at CIFAR-class shapes — measured
on v5e, a vmapped 3x3/16ch conv fwd+bwd is ~10x slower than the same math
with shared kernels, and the kernel-gradient is the dominant term. The
entire gap is a lowering artifact: reshaping the cohort into *feature
groups* — activations ``[C,B,H,W,ci] -> [B,H,W,C*ci]``, kernels
``[C,kh,kw,ci,co] -> [kh,kw,ci,C*co]`` — turns the batched conv into ONE
grouped ``lax.conv_general_dilated`` with ``feature_group_count=C`` that is
bit-identical to the vmapped form and ~2.6x faster end-to-end through the
backward pass (the grouped kernel-grad tiles the MXU properly).

This module packages that rewrite as a JAX primitive triple, so models keep
ordinary per-example code and ``vmap``/``grad`` compose as usual:

- ``conv_fwd_p`` (y from x,w), ``conv_dx_p`` (dL/dx from dy,w),
  ``conv_dw_p`` (dL/dw from x,dy) — a set closed under transposition, each
  bilinear, mirroring how ``lax.conv`` itself is wired into autodiff.
- Unbatched, each lowers to the stock ``lax`` computation (no regression
  for single-model paths like evaluation or ``entry()``).
- Under ``vmap`` (the cohort axis), each lowers to the grouped form. The
  dx/dw grouped lowerings are derived from the ONE grouped forward by
  ``jax.linear_transpose``, so the three can never drift apart.

Because ``vmap(grad(f))`` applies AD rules before batching rules, the
backward ops that batching sees ARE these primitives — which is exactly why
a plain ``jax.custom_vjp``/``custom_vmap`` wrapper is not enough and a
primitive is required.

:class:`Conv2D` is the drop-in flax module used by the model zoo in place
of ``nn.Conv`` — parameter leaf names ("kernel"/"bias"), shapes, and
initializers match ``nn.Conv``. Module *scope* names differ from an
``nn.Conv``-based tree (flax auto-names by class: ``Conv2D_N`` vs
``Conv_N``), so variable trees are consistent within this zoo but not
with checkpoints written by a pre-Conv2D build.

Reference context: the reference trains clients serially in torch
(``fedml_api/standalone/fedavg/fedavg_api.py:40-81``), so it never meets
this problem; it is created by the TPU-native "whole cohort in one XLA
program" design and solved here at the compiler-lowering level.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.core import ShapedArray
from jax.extend import core as jex_core
from jax.interpreters import ad, batching, mlir

DN = ("NHWC", "HWIO", "NHWC")


def _resolve_padding(
    padding, in_spatial, kernel_spatial, strides, rhs_dilation,
    lhs_dilation=(1, 1),
) -> tuple[tuple[int, int], ...]:
    """Resolve "SAME"/"VALID"/explicit padding to explicit (lo, hi) pairs
    (primitive params must not depend on operand shapes at rule time).
    With input (lhs) dilation, SAME is resolved against the dilated
    extent — transposed convs compute their own explicit pairs instead
    (see :func:`_conv_transpose_pads`)."""
    if isinstance(padding, str):
        pad = padding.upper()
        if pad == "VALID":
            return tuple((0, 0) for _ in in_spatial)
        if pad == "SAME":
            out = []
            for i, k, s, d, ld in zip(
                in_spatial, kernel_spatial, strides, rhs_dilation,
                lhs_dilation,
            ):
                i = (i - 1) * ld + 1
                eff_k = (k - 1) * d + 1
                o = -(-i // s)  # ceil
                total = max((o - 1) * s + eff_k - i, 0)
                out.append((total // 2, total - total // 2))
            return tuple(out)
        raise ValueError(
            f"unknown padding {padding!r} (supported: 'SAME', 'VALID', "
            "int, per-dim ints, or explicit (lo, hi) pairs; nn.Conv's "
            "'CIRCULAR' is not implemented here)"
        )
    # nn.Conv also accepts a single int or a per-dimension sequence of
    # ints; normalize them to (lo, hi) pairs to keep the drop-in contract
    if isinstance(padding, int):
        return tuple((padding, padding) for _ in in_spatial)
    return tuple(
        (int(p), int(p)) if isinstance(p, int) else (int(p[0]), int(p[1]))
        for p in padding
    )


def _conv_transpose_pads(k, s, padding):
    """Explicit (lo, hi) padding of the fractionally-strided conv that
    realizes a transposed conv — same rule as ``lax.conv_transpose``
    (SAME: out = in*s; VALID: out = in*s + max(k-s, 0))."""
    if padding == "SAME":
        pad_len = k + s - 2
        pad_a = k - 1 if s > k - 1 else -(-pad_len // 2)
    elif padding == "VALID":
        pad_len = k + s - 2 + max(k - s, 0)
        pad_a = k - 1
    else:
        raise ValueError(f"unknown transpose padding {padding!r}")
    return (pad_a, pad_len - pad_a)


def _out_spatial(i, pad, k, s, d, ld=1):
    i = (i - 1) * ld + 1
    eff_k = (k - 1) * d + 1
    return (i + pad[0] + pad[1] - eff_k) // s + 1


# ---------------------------------------------------------------------------
# Stock (unbatched) lowerings
# ---------------------------------------------------------------------------


# Minimum per-group width for supergroup packing — DISABLED by default
# (10**9). Measured on v5e: packing 64-wide groups to 128 lanes wins
# ~1.5x per-op in isolation (XLA dense-expands narrow groups, so the
# isolated grouped conv runs at ~15% useful MFU vs ~45% packed), but
# INSIDE the full fat-model gradient the same rewrite is a net 1.3x
# REGRESSION (57ms -> 75ms round): the kernel-construction ops defeat
# XLA's conv/BN fusion choices around every conv. Kept (with exact
# numerics, tested) for experimentation via FEDML_TPU_PACK_MIN_CIG=64;
# see docs/PERFORMANCE.md for the measurement story.
import os as _os

_PACK_MIN_CIG = int(_os.environ.get("FEDML_TPU_PACK_MIN_CIG", str(10**9)))


def _pack_factor(cig: int, groups: int) -> int:
    """How many adjacent groups to pack block-diagonally into one
    supergroup so per-group input width reaches the MXU's 128 lanes.

    XLA lowers grouped convolutions with narrow groups by DENSE EXPANSION
    (measured on v5e: a 10-group 64-ch/group conv costs the same as the
    full 640-ch dense conv — 10x the useful FLOPs), but lowers >=128-wide
    groups natively at ~45% MFU fwd+bwd. Packing ``p`` adjacent groups
    into one group with a block-diagonal kernel trades ``p``x FLOPs
    (p << groups) for the native lowering. Returns the smallest PROPER
    divisor of ``groups`` whose packed width reaches 128 lanes
    (p == groups would just re-create the dense expansion); 1 (stock
    path) when none does, when groups are already wide, or for
    depthwise-class convs (cig < 16 — XLA's dedicated depthwise lowering
    beats a ~128x FLOP inflation on bandwidth-bound ops)."""
    if cig >= 128 or groups == 1 or cig < max(_PACK_MIN_CIG, 16):
        # cig < 16 is a hard floor regardless of the env knob: depthwise-
        # class convs have XLA's dedicated lowering, and a >=128-lane
        # block-diagonal form would inflate their FLOPs ~16-128x.
        return 1
    for p in range(2, groups):
        if groups % p == 0 and cig * p >= 128:
            return p
    return 1


def _pack_blockdiag(w, fgc: int, pack: int):
    """Rewrite a grouped-conv kernel ``[kh, kw, cig, co]`` (out channels
    group-major over ``fgc`` groups) as the equivalent supergrouped kernel
    ``[kh, kw, pack*cig, co]`` for ``fgc // pack`` groups: each supergroup
    packs ``pack`` adjacent groups block-diagonally, off-diagonal blocks
    exact zeros (values unchanged — x + 0 is exact)."""
    kh, kw, cig, co = w.shape
    cog = co // fgc
    # Row-block p_i of the supergroup kernel holds w's columns whose
    # group lands at pack-position p_i, zeros elsewhere. Built as pack
    # mask-multiplies + one concat (elementwise, fusion-friendly; its
    # linear transpose — the dw path — is mask-multiplies of the split
    # gradient, equally cheap). No scatters or high-rank transposes:
    # those lowered badly inside large programs on TPU.
    group_pos = (jnp.arange(co) // cog) % pack
    blocks = [
        w * (group_pos == p_i).astype(w.dtype) for p_i in range(pack)
    ]
    return jnp.concatenate(blocks, axis=2)


def _lax_fwd(x, w, *, strides, padding, fgc, rhs_dilation,
             lhs_dilation=(1, 1), **_):
    pack = _pack_factor(w.shape[2], fgc)
    if pack > 1:
        w = _pack_blockdiag(w, fgc, pack)
        fgc = fgc // pack
    return lax.conv_general_dilated(
        x,
        w,
        window_strides=strides,
        padding=padding,
        lhs_dilation=lhs_dilation,
        rhs_dilation=rhs_dilation,
        dimension_numbers=DN,
        feature_group_count=fgc,
    )


def _lax_dx(dy, w, *, lhs_shape, **params):
    x_aval = jax.ShapeDtypeStruct(lhs_shape, dy.dtype)
    f = lambda xx: _lax_fwd(xx, w, **params)
    return jax.linear_transpose(f, x_aval)(dy)[0]


def _lax_dw(x, dy, *, rhs_shape, **params):
    w_aval = jax.ShapeDtypeStruct(rhs_shape, dy.dtype)
    f = lambda ww: _lax_fwd(x, ww, **params)
    return jax.linear_transpose(f, w_aval)(dy)[0]


# ---------------------------------------------------------------------------
# Cohort-grouped (batched) lowerings
# ---------------------------------------------------------------------------


def _cohort_fwd(x_b, w_b, *, strides, padding, fgc, rhs_dilation,
                lhs_dilation=(1, 1), **_):
    """Batched-over-(x, w) conv as ONE grouped conv: clients become channel
    groups. Bit-identical to ``vmap(conv)`` — group c of the grouped conv
    sees exactly client c's channels and kernel. Narrow groups are then
    supergroup-packed by :func:`_lax_fwd` exactly like the unbatched
    (cohort-grouped-model) path."""
    C, B, H, W, ci = x_b.shape
    _, kh, kw, cig, co = w_b.shape
    xg = x_b.transpose(1, 2, 3, 0, 4).reshape(B, H, W, C * ci)
    wg = w_b.transpose(1, 2, 3, 0, 4).reshape(kh, kw, cig, C * co)
    yg = _lax_fwd(
        xg, wg, strides=strides, padding=padding, fgc=C * fgc,
        rhs_dilation=rhs_dilation, lhs_dilation=lhs_dilation,
    )
    Ho, Wo = yg.shape[1], yg.shape[2]
    return yg.reshape(B, Ho, Wo, C, co).transpose(3, 0, 1, 2, 4)


def _lift(operand, bdim, size):
    """Bring the batch dim to axis 0, broadcasting unbatched operands —
    every batching rule then only handles the both-batched case."""
    if bdim is None:
        return jnp.broadcast_to(operand[None], (size,) + operand.shape)
    return jnp.moveaxis(operand, bdim, 0)


def _batch_size(args, dims):
    for a, d in zip(args, dims):
        if d is not None:
            return a.shape[d]
    raise AssertionError("no batched operand")


def _fwd_batch(args, dims, **params):
    x, w = args
    xd, wd = dims
    if wd is None:
        # kernels shared: fold the extra axis into the conv batch (strictly
        # better than the grouped form — no kernel replication)
        xb = jnp.moveaxis(x, xd, 0)
        C, B = xb.shape[0], xb.shape[1]
        y = _lax_fwd(xb.reshape((C * B,) + xb.shape[2:]), w, **params)
        return y.reshape((C, B) + y.shape[1:]), 0
    size = _batch_size(args, dims)
    xb = _lift(x, xd, size)
    wb = _lift(w, wd, size)
    return _cohort_fwd(xb, wb, **params), 0


def _dx_batch(args, dims, *, lhs_shape, **params):
    dy, w = args
    size = _batch_size(args, dims)
    dyb = _lift(dy, dims[0], size)
    wb = _lift(w, dims[1], size)
    x_aval = jax.ShapeDtypeStruct((size,) + tuple(lhs_shape), dyb.dtype)
    f = lambda xx: _cohort_fwd(xx, wb, **params)
    return jax.linear_transpose(f, x_aval)(dyb)[0], 0


def _dw_batch(args, dims, *, rhs_shape, lhs_shape, **params):
    x, dy = args
    size = _batch_size(args, dims)
    xb = _lift(x, dims[0], size)
    dyb = _lift(dy, dims[1], size)
    w_aval = jax.ShapeDtypeStruct((size,) + tuple(rhs_shape), dyb.dtype)
    f = lambda ww: _cohort_fwd(xb, ww, **params)
    return jax.linear_transpose(f, w_aval)(dyb)[0], 0


# ---------------------------------------------------------------------------
# Primitives
# ---------------------------------------------------------------------------


def _make(name, impl, batch_rule, abstract):
    p = jex_core.Primitive(name)
    p.def_impl(impl)
    p.def_abstract_eval(abstract)
    mlir.register_lowering(p, mlir.lower_fun(impl, multiple_results=False))
    batching.primitive_batchers[p] = batch_rule
    return p


def _fwd_abstract(x, w, *, strides, padding, rhs_dilation, rhs_shape,
                  lhs_dilation=(1, 1), **_):
    spatial = tuple(
        _out_spatial(i, p, k, s, d, ld)
        for i, p, k, s, d, ld in zip(
            x.shape[1:3], padding, rhs_shape[:2], strides, rhs_dilation,
            lhs_dilation,
        )
    )
    return ShapedArray(
        (x.shape[0],) + spatial + (rhs_shape[-1],), x.dtype
    )


def _dx_abstract(dy, w, *, lhs_shape, **_):
    return ShapedArray(tuple(lhs_shape), dy.dtype)


def _dw_abstract(x, dy, *, rhs_shape, **_):
    return ShapedArray(tuple(rhs_shape), dy.dtype)


conv_fwd_p = _make("cohort_conv_fwd", _lax_fwd, _fwd_batch, _fwd_abstract)
conv_dx_p = _make("cohort_conv_dx", _lax_dx, _dx_batch, _dx_abstract)
conv_dw_p = _make("cohort_conv_dw", _lax_dw, _dw_batch, _dw_abstract)

# Bilinear AD wiring, mirroring lax.conv: jvp reuses the same primitive on
# tangents; transposes map within the closed {fwd, dx, dw} set, so every
# op the backward pass emits still carries the cohort batching rules.
ad.defbilinear(
    conv_fwd_p,
    lambda ct, x, w, **kw: conv_dx_p.bind(ct, w, **kw),
    lambda ct, x, w, **kw: conv_dw_p.bind(x, ct, **kw),
)
ad.defbilinear(
    conv_dx_p,
    lambda ct, dy, w, **kw: conv_fwd_p.bind(ct, w, **kw),
    lambda ct, dy, w, **kw: conv_dw_p.bind(ct, dy, **kw),
)
ad.defbilinear(
    conv_dw_p,
    lambda ct, x, dy, **kw: conv_dx_p.bind(dy, ct, **kw),
    lambda ct, x, dy, **kw: conv_fwd_p.bind(x, ct, **kw),
)


def cohort_conv(
    x: jax.Array,
    kernel: jax.Array,
    strides: Sequence[int] = (1, 1),
    padding: Any = "SAME",
    feature_group_count: int = 1,
    rhs_dilation: Sequence[int] = (1, 1),
    lhs_dilation: Sequence[int] = (1, 1),
) -> jax.Array:
    """2-D convolution (NHWC x HWIO -> NHWC) with cohort-aware batching.

    Semantically identical to ``lax.conv_general_dilated``; under ``vmap``
    over both operands it lowers to a single grouped convolution.
    ``lhs_dilation`` gives the fractionally-strided form used by
    transposed convolutions (:class:`ConvTranspose2D`).
    """
    strides = tuple(int(s) for s in strides)
    rhs_dilation = tuple(int(d) for d in rhs_dilation)
    lhs_dilation = tuple(int(d) for d in lhs_dilation)
    pad = _resolve_padding(
        padding, x.shape[1:3], kernel.shape[:2], strides, rhs_dilation,
        lhs_dilation,
    )
    if x.dtype != kernel.dtype:
        ct = jnp.promote_types(x.dtype, kernel.dtype)
        x, kernel = x.astype(ct), kernel.astype(ct)
    return conv_fwd_p.bind(
        x,
        kernel,
        strides=strides,
        padding=pad,
        fgc=int(feature_group_count),
        rhs_dilation=rhs_dilation,
        lhs_dilation=lhs_dilation,
        lhs_shape=tuple(x.shape),
        rhs_shape=tuple(kernel.shape),
    )


# ---------------------------------------------------------------------------
# Drop-in flax module
# ---------------------------------------------------------------------------

import flax.linen as nn  # noqa: E402  (after primitive setup)


class Conv2D(nn.Module):
    """Drop-in for the zoo's uses of ``nn.Conv`` (2-D, NHWC), backed by
    :func:`cohort_conv`. Parameter names ("kernel", "bias"), shapes, and
    initializers match ``nn.Conv``, so variable trees are interchangeable.
    """

    features: int
    kernel_size: Sequence[int]
    strides: Sequence[int] = (1, 1)
    padding: Any = "SAME"
    use_bias: bool = True
    feature_group_count: int = 1
    # same keyword as nn.Conv; an int applies to both spatial dims
    kernel_dilation: Any = (1, 1)
    kernel_init: Any = nn.initializers.lecun_normal()
    bias_init: Any = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x):
        kh, kw = self.kernel_size
        cin = x.shape[-1]
        kernel = self.param(
            "kernel",
            self.kernel_init,
            (kh, kw, cin // self.feature_group_count, self.features),
        )
        if x.dtype != kernel.dtype:
            # mixed precision: follow the activation dtype (bf16 compute
            # casts params at the loss_fn boundary; this is belt-and-braces
            # for direct eval calls)
            kernel = kernel.astype(jnp.promote_types(x.dtype, kernel.dtype))
            x = x.astype(kernel.dtype)
        kd = self.kernel_dilation
        if kd is None:  # nn.Conv also treats None as no dilation
            kd = (1, 1)
        elif isinstance(kd, int):
            kd = (kd, kd)
        y = cohort_conv(
            x,
            kernel,
            strides=self.strides,
            padding=self.padding,
            feature_group_count=self.feature_group_count,
            rhs_dilation=kd,
        )
        if self.use_bias:
            bias = self.param("bias", self.bias_init, (self.features,))
            y = y + bias.astype(y.dtype)
        return y


class ConvTranspose2D(nn.Module):
    """Drop-in for the zoo's uses of ``nn.ConvTranspose`` (2-D, NHWC),
    backed by :func:`cohort_conv` in fractionally-strided form
    (``lhs_dilation = strides``, explicit transpose padding, unit window
    strides — the same realization ``lax.conv_transpose`` uses, kernel
    unflipped). Parameter names, shapes, and initializers match
    ``nn.ConvTranspose``, so generators vmapped over per-client params
    get the grouped cohort lowering for free."""

    features: int
    kernel_size: Sequence[int]
    strides: Sequence[int] = (1, 1)
    padding: str = "SAME"
    use_bias: bool = True
    # cohort-grouped form (models.cohort / the gan cohort pyramid):
    # channel group c is client c, kernel cin is per-group
    feature_group_count: int = 1
    kernel_init: Any = nn.initializers.lecun_normal()
    bias_init: Any = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x):
        kh, kw = self.kernel_size
        cin = x.shape[-1]
        kernel = self.param(
            "kernel",
            self.kernel_init,
            (kh, kw, cin // self.feature_group_count, self.features),
        )
        if x.dtype != kernel.dtype:
            kernel = kernel.astype(jnp.promote_types(x.dtype, kernel.dtype))
            x = x.astype(kernel.dtype)
        pads = tuple(
            _conv_transpose_pads(k, s, self.padding)
            for k, s in zip((kh, kw), self.strides)
        )
        y = cohort_conv(
            x,
            kernel,
            strides=(1, 1),
            padding=pads,
            feature_group_count=self.feature_group_count,
            lhs_dilation=self.strides,
        )
        if self.use_bias:
            bias = self.param("bias", self.bias_init, (self.features,))
            y = y + bias.astype(y.dtype)
        return y

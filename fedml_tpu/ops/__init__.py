"""TPU ops: pallas kernels + collective attention primitives."""

from fedml_tpu.ops.ring_attention import ring_attention  # noqa: F401

"""Pallas flash attention: the single-chip hot-path attention kernel.

Blockwise attention with online softmax, tiled for VMEM: the grid walks
(batch*heads, Q blocks); each program streams K/V blocks of the full
sequence through VMEM scratch, keeping the running (max, sum, output)
statistics in registers/VMEM — HBM traffic is O(T) per Q block instead of
materializing the [T, T] score matrix.

On non-TPU backends (the CI's virtual CPU mesh) the kernel runs in pallas
interpret mode; for large sequences prefer the compiled XLA fallback
(:func:`fedml_tpu.ops.ring_attention.full_attention`) on CPU.

Measured honestly on v5e (B=4, H=8, D=64, bf16, causal): XLA's fused
attention (``full_attention``) is 6-11x FASTER than this kernel at
T=2048-8192 — the XLA TPU attention fusion is excellent and this
hand-tiled kernel does not beat it. ``TransformerLM`` therefore defaults
to ``full_attention``; use this kernel when the [T, T] score matrix must
never materialize in HBM at sequence lengths where XLA's fusion would
spill (or shard the sequence with
:func:`fedml_tpu.ops.ring_attention.ring_attention` instead).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int,
                  causal: bool, q_block: int):
    qi = pl.program_id(1)
    q = q_ref[...].astype(jnp.float32)  # [Bq, D]
    d = q.shape[-1]
    scale = 1.0 / (d ** 0.5)
    t_total = k_ref.shape[0]
    n_kb = t_total // block_k

    m0 = jnp.full((q.shape[0],), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((q.shape[0],), jnp.float32)
    o0 = jnp.zeros(q.shape, jnp.float32)

    q_pos = qi * q_block + jax.lax.iota(jnp.int32, q.shape[0])

    def body(kb, carry):
        o_acc, m_acc, l_acc = carry
        k = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [Bq, Bk]
        if causal:
            k_pos = kb * block_k + jax.lax.iota(jnp.int32, block_k)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask, s, -jnp.inf)
        m_b = jnp.max(s, axis=-1)
        p = jnp.where(
            jnp.isfinite(m_b)[:, None], jnp.exp(s - m_b[:, None]), 0.0
        )
        l_b = jnp.sum(p, axis=-1)
        o_b = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        new_m = jnp.maximum(m_acc, m_b)
        alpha = jnp.where(
            jnp.isfinite(m_acc), jnp.exp(m_acc - new_m), 0.0
        )
        beta = jnp.where(jnp.isfinite(m_b), jnp.exp(m_b - new_m), 0.0)
        return (
            o_acc * alpha[:, None] + o_b * beta[:, None],
            new_m,
            l_acc * alpha + l_b * beta,
        )

    if causal:
        # skip K blocks strictly after this Q block
        n_run = jnp.minimum(
            (qi + 1) * q_block // block_k + 1, n_kb
        )
    else:
        n_run = n_kb
    o, m, l = jax.lax.fori_loop(0, n_run, body, (o0, m0, l0))
    o_ref[...] = (o / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jax.Array:
    """[B, T, H, D] attention via the pallas kernel. ``interpret`` defaults
    to True off-TPU so tests run on the virtual CPU mesh."""
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    b, t, h, d = q.shape
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    assert t % block_q == 0 and t % block_k == 0, (t, block_q, block_k)

    # fold batch and heads into the grid's first axis; kernel sees [T, D]
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    kernel = functools.partial(
        _flash_kernel, block_k=block_k, causal=causal, q_block=block_q
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)

"""Ring attention: exact long-context attention over a sequence-parallel
mesh axis.

The reference has no attention models at all (SURVEY.md §5.7) — this is a
capability the TPU build adds as first-class: sequences are sharded over a
mesh axis; each device keeps its Q shard resident while K/V shards rotate
around the ring via ``ppermute`` (ICI neighbor exchange), accumulating with
an online-softmax (flash-attention style, Liu et al. "Ring Attention with
Blockwise Transformers"). Communication overlaps compute: each of the
``p`` steps moves one K/V block while the MXU contracts the previous one.

Usage (inside ``shard_map`` over the sequence axis)::

    out = ring_attention(q, k, v, axis_name="sp", causal=True)

``q, k, v``: [B, T_local, H, D] shards; returns [B, T_local, H, D].
Numerics: accumulation in float32 regardless of input dtype.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _block_attn(q, k, v, bias):
    """One (Q-block, K-block) attention contribution.

    Returns (o_unnorm [B,Tq,H,D] f32, row_max [B,H,Tq] f32,
    row_sum [B,H,Tq] f32) for online-softmax merging.
    """
    scale = 1.0 / jnp.sqrt(q.shape[-1])
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1)  # [B,H,Tq]
    # a fully-masked row has m = -inf; exp(-inf - -inf) would be NaN, and
    # a NaN in the UNSELECTED where-branch still poisons gradients, so
    # sanitize m before subtracting (double-where trick)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.where(
        jnp.isfinite(m)[..., None], jnp.exp(s - m_safe[..., None]), 0.0
    )
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return o, m, l


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
) -> jax.Array:
    """Exact attention with sequence shards rotating K/V around the ring.

    Must run inside ``shard_map``/``pjit`` with ``axis_name`` a mesh axis
    of size p; T_global = p * T_local.
    """
    p = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, t_local, h, d = q.shape

    q32 = q.astype(jnp.float32)
    perm = [(i, (i + 1) % p) for i in range(p)]

    # positions for causal masking
    q_pos = my * t_local + jnp.arange(t_local)  # [Tq]

    def body(step, carry):
        o_acc, m_acc, l_acc, k_cur, v_cur = carry
        # origin of the K/V block currently held: it has been forwarded
        # `step` times along the +1 ring, so it started at (my - step) % p
        origin = (my - step) % p
        if causal:
            k_pos = origin * t_local + jnp.arange(t_local)  # [Tk]
            mask = q_pos[:, None] >= k_pos[None, :]  # [Tq, Tk]
            bias = jnp.where(mask, 0.0, -jnp.inf)[None, None]
        else:
            bias = None
        o_b, m_b, l_b = _block_attn(q32, k_cur, v_cur, bias)

        # online-softmax merge (flash-attention rescaling). All operands
        # are sanitized BEFORE subtraction: -inf - -inf = NaN inside an
        # unselected where-branch would still poison the backward pass.
        new_m = jnp.maximum(m_acc, m_b)
        new_m_safe = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        fin_acc = jnp.isfinite(m_acc)
        fin_b = jnp.isfinite(m_b)
        alpha = jnp.where(
            fin_acc,
            jnp.exp(jnp.where(fin_acc, m_acc, 0.0) - new_m_safe),
            0.0,
        )
        beta = jnp.where(
            fin_b, jnp.exp(jnp.where(fin_b, m_b, 0.0) - new_m_safe), 0.0
        )
        l_new = l_acc * alpha + l_b * beta
        o_new = (
            o_acc * alpha.transpose(0, 2, 1)[..., None]
            + o_b * beta.transpose(0, 2, 1)[..., None]
        )
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return o_new, new_m, l_new, k_nxt, v_nxt

    o0 = jnp.zeros((b, t_local, h, d), jnp.float32)
    m0 = jnp.full((b, h, t_local), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, t_local), jnp.float32)
    # newer shard_map tracks varying-manual-axes: literal-initialized
    # carries must be marked as varying over the ring axis or the loop
    # carry types mismatch. jax.lax.pcast(to='varying') is the current
    # spelling; fall back to the deprecated pvary on older jax.
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        mark = lambda a: pcast(a, (axis_name,), to="varying")
    else:
        pvary = getattr(jax.lax, "pvary", None)
        mark = (lambda a: pvary(a, (axis_name,))) if pvary else (lambda a: a)
    o0, m0, l0 = (mark(a) for a in (o0, m0, l0))
    o, m, l, _, _ = jax.lax.fori_loop(
        0, p, body, (o0, m0, l0, k.astype(jnp.float32), v.astype(jnp.float32))
    )
    # rows with no visible keys (can't happen for causal with step 0
    # including self, but guard anyway)
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)


def full_attention(q, k, v, causal: bool = False) -> jax.Array:
    """Single-device reference: plain softmax attention (the oracle for
    ring/flash tests)."""
    scale = 1.0 / jnp.sqrt(q.shape[-1])
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v
    ).astype(q.dtype)


def make_sequence_parallel_attention(mesh, axis_name: str, causal: bool):
    """Wrap :func:`ring_attention` in a ``shard_map`` over ``axis_name``:
    takes/returns GLOBAL [B, T, H, D] arrays sharded on T."""
    from jax.sharding import PartitionSpec as P

    from fedml_tpu.core.compat import shard_map

    spec = P(None, axis_name, None, None)
    fn = functools.partial(
        ring_attention, axis_name=axis_name, causal=causal
    )
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )

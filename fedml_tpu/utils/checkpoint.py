"""Round-state checkpoint / resume via orbax.

The reference has NO framework-level checkpointing (SURVEY.md §5.4 — only
algorithm-local ``torch.save`` in FedGKT/DARTS); this is the deliberate
upgrade the survey calls out: any sim state (a pytree NamedTuple like
``ServerState`` / ``FedGDKDState``) checkpoints atomically per round and a
run resumes from the latest step after preemption.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp


class RoundCheckpointer:
    """Save/restore per-round sim state.

    Usage::

        ckpt = RoundCheckpointer(dir, keep=3)
        state, start_round = ckpt.restore_or(state)   # resume if possible
        for r in range(start_round, rounds):
            state, _ = sim.run_round(state)
            ckpt.save(r, state)
    """

    def __init__(self, directory: str, keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True
            ),
        )

    def save(self, round_idx: int, state: Any) -> None:
        self._mgr.save(
            round_idx, args=ocp.args.StandardSave(_to_savable(state))
        )
        self._mgr.wait_until_finished()

    def latest_round(self) -> int | None:
        return self._mgr.latest_step()

    def restore_or(self, init_state: Any) -> tuple[Any, int]:
        """Return (state, next_round): the restored latest checkpoint if one
        exists, else ``(init_state, 0)``."""
        step = self._mgr.latest_step()
        if step is None:
            return init_state, 0
        template = _to_savable(init_state)
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(template)
        )
        return _from_savable(init_state, restored), step + 1

    def close(self):
        self._mgr.close()


def _to_savable(state: Any):
    """NamedTuples -> plain nested dict of arrays (orbax-friendly)."""
    if hasattr(state, "_asdict"):
        return {k: _to_savable(v) for k, v in state._asdict().items()}
    if isinstance(state, dict):
        return {k: _to_savable(v) for k, v in state.items()}
    if isinstance(state, (list, tuple)):
        return {f"__{i}": _to_savable(v) for i, v in enumerate(state)}
    return np.asarray(jax.device_get(state))


def _from_savable(template: Any, blob: Any):
    """Rebuild the original container types from the saved dict."""
    if hasattr(template, "_asdict"):
        return type(template)(
            **{
                k: _from_savable(v, blob[k])
                for k, v in template._asdict().items()
            }
        )
    if isinstance(template, dict):
        return {k: _from_savable(v, blob[k]) for k, v in template.items()}
    if isinstance(template, (list, tuple)):
        vals = [
            _from_savable(v, blob[f"__{i}"])
            for i, v in enumerate(template)
        ]
        return type(template)(vals)
    import jax.numpy as jnp

    arr = jnp.asarray(blob)
    tmpl = jnp.asarray(template)
    return arr.astype(tmpl.dtype) if arr.dtype != tmpl.dtype else arr

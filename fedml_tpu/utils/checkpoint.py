"""Round-state checkpoint / resume via orbax.

The reference has NO framework-level checkpointing (SURVEY.md §5.4 — only
algorithm-local ``torch.save`` in FedGKT/DARTS); this is the deliberate
upgrade the survey calls out: any sim state (a pytree NamedTuple like
``ServerState`` / ``FedGDKDState``) checkpoints atomically per round and a
run resumes from the latest step after preemption.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp


class RoundCheckpointer:
    """Save/restore per-round sim state.

    Usage::

        ckpt = RoundCheckpointer(dir, keep=3)
        state, start_round = ckpt.restore_or(state)   # resume if possible
        for r in range(start_round, rounds):
            state, _ = sim.run_round(state)
            ckpt.save(r, state)
    """

    def __init__(self, directory: str, keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True
            ),
        )

    def save(self, round_idx: int, state: Any) -> None:
        self._mgr.save(
            round_idx, args=ocp.args.StandardSave(_to_savable(state))
        )
        self._mgr.wait_until_finished()

    def latest_round(self) -> int | None:
        return self._mgr.latest_step()

    def restore_raw(self) -> tuple[Any, int]:
        """Template-free restore of the latest checkpoint: ``(raw
        nested dict of numpy arrays, next_round)``, or ``(None, 0)``
        when no checkpoint exists. This is the elastic-world entry
        (docs/FAULT_TOLERANCE.md "Elastic membership"): the deploy
        server's composite payload carries VARIABLE-length membership /
        reputation arrays that a shape-templated ``restore_or`` cannot
        express — the caller reassembles typed state with
        :func:`from_savable` per component and adapts array sizes
        itself."""
        step = self._mgr.latest_step()
        if step is None:
            return None, 0
        # explicit template-free StandardRestore: a bare restore(step)
        # on a manager that never saved has no handler registered for
        # the item and raises KeyError on current orbax
        return (
            self._mgr.restore(step, args=ocp.args.StandardRestore()),
            step + 1,
        )

    def restore_or(self, init_state: Any) -> tuple[Any, int]:
        """Return (state, next_round): the restored latest checkpoint if one
        exists, else ``(init_state, 0)``.

        Checkpoints written by pre-``Conv2D`` builds of this repo carry
        flax auto-scopes named ``Conv_N``/``ConvTranspose_N`` (and
        auto-numbered ``Dense_N`` heads) where current trees say
        ``Conv2D_N``/``ConvTranspose2D_N``/named heads; such checkpoints
        are migrated on restore by :func:`_migrate_scopes` instead of
        failing the structure match. A composite checkpoint — the
        deploy server's ``{"server", "reputation", ...}`` payload, or
        the harness's ``{"server", "bank"}`` client-state save
        (docs/FAULT_TOLERANCE.md "Client-state banks") — restored
        against a bare sim-state template is unwrapped to its
        ``"server"`` payload, so a deploy run and a sim run of one
        config keep sharing the resume story in BOTH directions."""
        step = self._mgr.latest_step()
        if step is None:
            return init_state, 0
        template = _to_savable(init_state)
        try:
            restored = self._mgr.restore(
                step, args=ocp.args.StandardRestore(template)
            )
        except (ValueError, KeyError, TypeError) as err:
            # structure mismatch (e.g. legacy scope names): raw-restore
            # and remap keys against the template. Transient I/O errors
            # (OSError etc.) propagate directly — only the error classes
            # orbax raises for template/key mismatches enter the
            # migration path. Migration is strict
            # (unique shape matches only) and re-raises the ORIGINAL
            # error when it cannot resolve, so a wrong-experiment or
            # corrupted checkpoint still fails loudly instead of loading
            # cross-assigned weights.
            try:
                raw = self._mgr.restore(step)
                if (
                    isinstance(raw, dict)
                    and "server" in raw
                    # tolerate the known composite siblings: the deploy
                    # actor's reputation/membership/async planes and the
                    # harness's client-state banks
                    and set(raw) <= {"server", "reputation",
                                     "membership", "async", "bank"}
                    and not (isinstance(template, dict)
                             and "server" in template)
                ):
                    # a composite checkpoint (deploy-server planes, or
                    # the harness's {"server", "bank"} client-state
                    # save) restored by a bare-state caller: the round
                    # state is the "server" payload
                    raw = raw["server"]
                restored = _migrate_scopes(template, raw)
            except Exception:
                raise err
            import warnings

            warnings.warn(
                f"checkpoint at step {step} did not match the template "
                "directly (legacy scope names, or a deploy-server "
                "composite read by a sim); restored via structure "
                "migration",
                stacklevel=2,
            )
        return _from_savable(init_state, restored), step + 1

    def close(self):
        self._mgr.close()


def _to_savable(state: Any):
    """NamedTuples -> plain nested dict of arrays (orbax-friendly)."""
    if hasattr(state, "_asdict"):
        return {k: _to_savable(v) for k, v in state._asdict().items()}
    if isinstance(state, dict):
        return {k: _to_savable(v) for k, v in state.items()}
    if isinstance(state, (list, tuple)):
        return {f"__{i}": _to_savable(v) for i, v in enumerate(state)}
    return np.asarray(jax.device_get(state))


def _leaf_shapes(t) -> list[tuple]:
    return [tuple(np.shape(leaf)) for leaf in jax.tree.leaves(t)]


def _migrate_scopes(template: Any, blob: Any):
    """Remap a saved nested dict onto the template's key structure.

    Per dict level: exact key matches first; then the deterministic
    module renames (``Conv2D_N`` <- ``Conv_N``, ``ConvTranspose2D_N`` <-
    ``ConvTranspose_N``); finally, a leftover template key is paired
    with a leftover blob key only when its leaf-shape signature matches
    UNIQUELY (renamed heads like ``head``/``fc1`` vs legacy ``Dense_N``).
    Raises ``KeyError`` when a key cannot be resolved or the shape match
    is ambiguous — never guesses by order."""
    if not isinstance(template, dict):
        return blob
    if not isinstance(blob, dict):
        raise KeyError(f"checkpoint structure mismatch at {template!r}")
    out, used = {}, set()
    unresolved = []
    for k in template:
        if k in blob:
            out[k] = k
            used.add(k)
            continue
        legacy = (
            k.replace("Conv2D", "Conv")
            if "ConvTranspose2D" not in k
            else k.replace("ConvTranspose2D", "ConvTranspose")
        )
        if legacy != k and legacy in blob and legacy not in used:
            out[k] = legacy
            used.add(legacy)
        else:
            unresolved.append(k)
    spare = [k for k in blob if k not in used]
    for k in unresolved:
        matches = [
            b
            for b in spare
            if _leaf_shapes(template[k]) == _leaf_shapes(blob[b])
        ]
        if not matches:
            raise KeyError(
                f"cannot migrate checkpoint scope {k!r}; "
                f"unmatched saved scopes: {spare}"
            )
        if len(matches) > 1:
            # two spare scopes share the leaf signature: assigning by
            # order could silently cross-load weights — refuse
            raise KeyError(
                f"ambiguous checkpoint migration for scope {k!r}: "
                f"{matches} all match its leaf shapes"
            )
        out[k] = matches[0]
        spare.remove(matches[0])
    return {k: _migrate_scopes(template[k], blob[src])
            for k, src in out.items()}


def from_savable(template: Any, blob: Any):
    """Public face of :func:`_from_savable`: rebuild typed state (e.g.
    a ``ServerState``) from one component of a raw
    :meth:`RoundCheckpointer.restore_raw` payload. Falls back to the
    same strict structure migration ``restore_or`` applies (legacy
    ``Conv_N`` scope names), so the raw path loses none of the
    template path's compatibility."""
    try:
        return _from_savable(template, blob)
    except (KeyError, TypeError, ValueError) as err:
        try:
            migrated = _migrate_scopes(_to_savable(template), blob)
        except Exception:
            raise err
        import warnings

        warnings.warn(
            "checkpoint component did not match its template directly "
            "(legacy scope names); restored via structure migration",
            stacklevel=2,
        )
        return _from_savable(template, migrated)


def _from_savable(template: Any, blob: Any):
    """Rebuild the original container types from the saved dict."""
    if hasattr(template, "_asdict"):
        return type(template)(
            **{
                k: _from_savable(v, blob[k])
                for k, v in template._asdict().items()
            }
        )
    if isinstance(template, dict):
        return {k: _from_savable(v, blob[k]) for k, v in template.items()}
    if isinstance(template, (list, tuple)):
        vals = [
            _from_savable(v, blob[f"__{i}"])
            for i, v in enumerate(template)
        ]
        return type(template)(vals)
    import jax.numpy as jnp

    # jnp.array (copy=True), NOT asarray: a restored leaf is a numpy
    # array whose memory orbax owns, and asarray's CPU zero-copy alias
    # hands that memory to jax — a donating jit (FedAvgSim's round
    # donates its state) then overwrites/frees a buffer jax never owned,
    # which was a flaky SIGSEGV on every checkpoint-resume run.
    # dtype comes from the attribute when present — np.asarray on a live
    # device-array template would pull the whole leaf to host just to
    # read it.
    dtype = getattr(template, "dtype", None)
    if dtype is None:  # python scalar leaf
        dtype = np.result_type(template)
    return jnp.array(blob, dtype=dtype)

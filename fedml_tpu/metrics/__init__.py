from fedml_tpu.metrics.sink import MetricsSink

"""FID scoring: activation statistics + Frechet distance.

Reference: ``FID/FIDScorer.py:9`` (``calculate_activation_statistics:13``,
Frechet distance via ``scipy.linalg.sqrtm``) feeding a torchvision
InceptionV3 (``FID/InceptionV3.py``). The math here is identical; the
feature extractor is PLUGGABLE because pretrained Inception weights are not
available offline (zero egress) — the default is a fixed-seed random conv
embedding, which preserves FID's ordering behavior for tracking GAN
progress within a run (random-projection FID), and any flax module (e.g. a
trained classifier's penultimate layer) can be supplied for
reference-grade scoring.

The trace-sqrt term is computed eigenvalue-wise: for PSD S1, S2 the eigen-
values of S1 @ S2 are real non-negative, so
``tr(sqrt(S1 S2)) = sum(sqrt(eig(S1 S2)))`` — no scipy dependency.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np


def activation_statistics(feats: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(mu, sigma) of [N, D] features (reference
    ``calculate_activation_statistics``, ``FIDScorer.py:13-21``)."""
    mu = feats.mean(axis=0)
    sigma = np.cov(feats, rowvar=False)
    return mu, np.atleast_2d(sigma)


def frechet_distance(mu1, s1, mu2, s2, eps: float = 1e-6) -> float:
    """||mu1-mu2||^2 + tr(S1 + S2 - 2 sqrt(S1 S2)) (reference
    ``calculate_frechet_distance``)."""
    diff = mu1 - mu2
    prod = s1 @ s2
    eig = np.linalg.eigvals(prod)
    # numerical noise can push tiny eigenvalues slightly negative/complex
    tr_sqrt = np.sum(np.sqrt(np.maximum(np.real(eig), 0.0)))
    fid = float(diff @ diff + np.trace(s1) + np.trace(s2) - 2.0 * tr_sqrt)
    return max(fid, 0.0)


class _RandomConvEmbed:
    """Deterministic random conv features (LeCun-style random projection)."""

    def __init__(self, dim: int = 64, seed: int = 0):
        self.dim = dim
        self.seed = seed
        self._apply = None

    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        import flax.linen as nn

        if self._apply is None:
            dim = self.dim

            class Net(nn.Module):
                @nn.compact
                def __call__(self, x):
                    h = nn.Conv(32, (3, 3), strides=(2, 2))(x)
                    h = nn.relu(h)
                    h = nn.Conv(64, (3, 3), strides=(2, 2))(h)
                    h = nn.relu(h)
                    h = jnp.mean(h, axis=(1, 2))
                    return nn.Dense(dim)(h)

            net = Net()
            variables = net.init(
                jax.random.key(self.seed), jnp.zeros((1,) + x.shape[1:])
            )
            self._apply = jax.jit(lambda a: net.apply(variables, a))
        return self._apply(x)


class TrainedCNNEmbed:
    """Offline-REPRODUCIBLE feature extractor: a small flax CNN classifier
    trained deterministically (fixed seed, fixed batch order, few epochs)
    on the eval split, exposing penultimate-layer features.

    This is the default scorer wherever labeled real data exists: unlike
    the random projection it embeds images in a space that separates the
    classes, so FID tracks sample QUALITY rather than raw pixel
    statistics — and unlike pretrained Inception it needs no weights file
    (zero-egress hosts). Two processes on the same data and backend
    produce identical features, hence identical FID (pinned in
    tests/test_support.py)."""

    def __init__(self, variables, apply_fn):
        self._variables = variables
        self._apply = jax.jit(apply_fn)

    @classmethod
    def fit(cls, images, labels, num_classes: int | None = None,
            dim: int = 64, epochs: int = 3, batch_size: int = 128,
            lr: float = 1e-3, seed: int = 0):
        import flax.linen as nn
        import optax

        images = jnp.asarray(images, jnp.float32)
        labels = jnp.asarray(labels, jnp.int32)
        k = int(num_classes or int(labels.max()) + 1)

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                h = nn.relu(nn.Conv(32, (3, 3), strides=(2, 2))(x))
                h = nn.relu(nn.Conv(64, (3, 3), strides=(2, 2))(h))
                h = jnp.mean(h, axis=(1, 2))
                feat = nn.Dense(dim, name="feat")(h)
                logits = nn.Dense(k, name="cls")(nn.relu(feat))
                return feat, logits

        net = Net()
        key = jax.random.key(seed)
        variables = net.init(key, images[:1])
        opt = optax.adam(lr)
        opt_state = opt.init(variables["params"])

        @jax.jit
        def step(params, opt_state, xb, yb):
            def loss_fn(p):
                _, logits = net.apply({"params": p}, xb)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, yb
                ).mean()

            grads = jax.grad(loss_fn)(params)
            updates, opt_state = opt.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state

        params = variables["params"]
        n = images.shape[0]
        # small eval splits must still TRAIN (an empty step range would
        # silently return random-init weights sold as a trained embed)
        batch_size = max(1, min(batch_size, n))
        for e in range(epochs):
            perm = jax.random.permutation(
                jax.random.fold_in(key, e + 1), n
            )
            for s in range(0, n - batch_size + 1, batch_size):
                take = perm[s:s + batch_size]
                params, opt_state = step(
                    params, opt_state, images[take], labels[take]
                )
        return cls(
            {"params": params},
            lambda x: net.apply({"params": params}, x)[0],
        )

    def __call__(self, x) -> np.ndarray:
        return np.asarray(self._apply(jnp.asarray(x, jnp.float32)))


def sample_grid(images, rows: int = 8, cols: int = 8) -> np.ndarray:
    """Tile [N, H, W, C] images into one [rows*H, cols*W, C] grid array
    (the reference logs torchvision ``make_grid`` images each round,
    ``fedgdkd/server.py:140-165``)."""
    images = np.asarray(images)
    n, h, w, c = images.shape
    need = rows * cols
    if n < need:
        pad = np.zeros((need - n, h, w, c), images.dtype)
        images = np.concatenate([images, pad])
    grid = images[:need].reshape(rows, cols, h, w, c)
    return grid.transpose(0, 2, 1, 3, 4).reshape(rows * h, cols * w, c)


_DEFAULT_SCORER = None


def _default_scorer():
    """One shared default scorer: rebuilding per round would re-load the
    TorchScript Inception (when configured) or re-jit the embed every
    call."""
    global _DEFAULT_SCORER
    if _DEFAULT_SCORER is None:
        _DEFAULT_SCORER = make_fid_scorer()
    return _DEFAULT_SCORER


def log_gan_round(sink, sim, state, round_idx: int, scorer=None,
                  n_samples: int = 64, out_dir: str | None = None,
                  extra: dict | None = None) -> dict:
    """Per-round GAN observability: FID(real eval split, fresh samples) +
    a sample grid saved as .npy next to the sink, one JSONL record
    (reference ``fedgdkd/server.py:140-165`` logs FID + image grids per
    round)."""
    import os

    fake = np.asarray(sim.sample_images(state, n_samples, seed=round_idx))
    real = np.asarray(sim.arrays.test_x[:max(n_samples, 256)])
    scorer = scorer or _default_scorer()
    fid = scorer.calculate_fid(real, fake)
    record = {"round": round_idx, "fid": float(fid), **(extra or {})}
    base = out_dir or (
        (os.path.dirname(sink.path) or ".") if sink.path else None
    )
    if base:
        os.makedirs(base, exist_ok=True)
        grid_path = os.path.join(
            base, f"gan_samples_r{round_idx:05d}.npy"
        )
        np.save(grid_path, sample_grid(fake))
        record["sample_grid"] = grid_path
    sink.log(record)
    return record


class FIDScorer:
    """Drop-in for the reference ``FIDScorer`` with a pluggable embed.

    ``embed_fn(x[B,H,W,C]) -> [B,D]``; defaults to the fixed random conv
    embedding (see module docstring for why).
    """

    def __init__(
        self,
        embed_fn: Callable | None = None,
        batch_size: int = 256,
    ):
        self.embed_fn = embed_fn or _RandomConvEmbed()
        self.batch_size = batch_size

    def _features(self, images) -> np.ndarray:
        feats = []
        n = images.shape[0]
        for s in range(0, n, self.batch_size):
            feats.append(
                np.asarray(self.embed_fn(jnp.asarray(images[s:s + self.batch_size])))
            )
        return np.concatenate(feats)

    def calculate_fid(self, images_real, images_fake) -> float:
        """(reference ``calculate_fid``, logged each round by
        ``fedgdkd/server.py:144-154``)."""
        mu1, s1 = activation_statistics(self._features(images_real))
        mu2, s2 = activation_statistics(self._features(images_fake))
        return frechet_distance(mu1, s1, mu2, s2)


class TorchScriptEmbed:
    """Real-InceptionV3 (or any) feature extractor from a TorchScript file.

    The reference scores FID with torchvision's pretrained InceptionV3
    (``FID/InceptionV3.py``); its weights are not shipped offline. When a
    scripted module IS available on disk (e.g. exported once with
    ``torch.jit.script(torchvision...inception_v3(...))``), this hook runs
    it on CPU via ``torch.jit.load`` — no torchvision dependency — making
    the resulting FID numbers comparable to published values.

    Input convention: NHWC float in [0, 1]; converted to NCHW, resized by
    nearest-neighbor to ``input_hw``, grayscale replicated to 3 channels.
    """

    def __init__(self, path: str, input_hw: int = 299):
        import torch

        self.torch = torch
        self.module = torch.jit.load(path, map_location="cpu").eval()
        self.input_hw = input_hw

    def __call__(self, x) -> np.ndarray:
        torch = self.torch
        arr = np.asarray(x, np.float32)
        if arr.shape[-1] == 1:
            arr = np.repeat(arr, 3, axis=-1)
        t = torch.from_numpy(np.transpose(arr, (0, 3, 1, 2)))
        if t.shape[-1] != self.input_hw:
            t = torch.nn.functional.interpolate(
                t, size=(self.input_hw, self.input_hw), mode="nearest"
            )
        with torch.no_grad():
            out = self.module(t)
        if isinstance(out, (tuple, list)):
            out = out[0]
        out = out.reshape(out.shape[0], -1)
        return out.numpy()


def make_fid_scorer(
    inception_path: str | None = None,
    batch_size: int = 64,
    train_data: tuple | None = None,
    num_classes: int | None = None,
    seed: int = 0,
) -> FIDScorer:
    """FIDScorer factory, in descending preference:

    1. real (TorchScript) Inception embed when a weights file is present
       (``inception_path`` or ``$FEDML_TPU_INCEPTION``) — numbers
       comparable to published FID;
    2. ``train_data=(images, labels)``: a deterministically TRAINED flax
       CNN embed (:class:`TrainedCNNEmbed`) — reproducible across
       processes/machines on the same data, class-aware features;
    3. the fixed-seed random-projection embed (ordering within a run
       only)."""
    import os

    path = inception_path or os.environ.get("FEDML_TPU_INCEPTION")
    if path:
        if not os.path.exists(path):
            # an explicitly requested extractor that is missing must NOT
            # silently degrade to the offline embed — the numbers would
            # look comparable to published FID but not be
            raise FileNotFoundError(
                f"Inception TorchScript file not found: {path}"
            )
        return FIDScorer(embed_fn=TorchScriptEmbed(path),
                         batch_size=batch_size)
    if train_data is not None:
        embed = TrainedCNNEmbed.fit(
            train_data[0], train_data[1], num_classes=num_classes,
            seed=seed,
        )
        return FIDScorer(embed_fn=embed, batch_size=batch_size)
    return FIDScorer(batch_size=batch_size)

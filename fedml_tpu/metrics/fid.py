"""FID scoring: activation statistics + Frechet distance.

Reference: ``FID/FIDScorer.py:9`` (``calculate_activation_statistics:13``,
Frechet distance via ``scipy.linalg.sqrtm``) feeding a torchvision
InceptionV3 (``FID/InceptionV3.py``). The math here is identical; the
feature extractor is PLUGGABLE because pretrained Inception weights are not
available offline (zero egress) — the default is a fixed-seed random conv
embedding, which preserves FID's ordering behavior for tracking GAN
progress within a run (random-projection FID), and any flax module (e.g. a
trained classifier's penultimate layer) can be supplied for
reference-grade scoring.

The trace-sqrt term is computed eigenvalue-wise: for PSD S1, S2 the eigen-
values of S1 @ S2 are real non-negative, so
``tr(sqrt(S1 S2)) = sum(sqrt(eig(S1 S2)))`` — no scipy dependency.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np


def activation_statistics(feats: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(mu, sigma) of [N, D] features (reference
    ``calculate_activation_statistics``, ``FIDScorer.py:13-21``)."""
    mu = feats.mean(axis=0)
    sigma = np.cov(feats, rowvar=False)
    return mu, np.atleast_2d(sigma)


def frechet_distance(mu1, s1, mu2, s2, eps: float = 1e-6) -> float:
    """||mu1-mu2||^2 + tr(S1 + S2 - 2 sqrt(S1 S2)) (reference
    ``calculate_frechet_distance``)."""
    diff = mu1 - mu2
    prod = s1 @ s2
    eig = np.linalg.eigvals(prod)
    # numerical noise can push tiny eigenvalues slightly negative/complex
    tr_sqrt = np.sum(np.sqrt(np.maximum(np.real(eig), 0.0)))
    fid = float(diff @ diff + np.trace(s1) + np.trace(s2) - 2.0 * tr_sqrt)
    return max(fid, 0.0)


class _RandomConvEmbed:
    """Deterministic random conv features (LeCun-style random projection)."""

    def __init__(self, dim: int = 64, seed: int = 0):
        self.dim = dim
        self.seed = seed
        self._apply = None

    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        import flax.linen as nn

        if self._apply is None:
            dim = self.dim

            class Net(nn.Module):
                @nn.compact
                def __call__(self, x):
                    h = nn.Conv(32, (3, 3), strides=(2, 2))(x)
                    h = nn.relu(h)
                    h = nn.Conv(64, (3, 3), strides=(2, 2))(h)
                    h = nn.relu(h)
                    h = jnp.mean(h, axis=(1, 2))
                    return nn.Dense(dim)(h)

            net = Net()
            variables = net.init(
                jax.random.key(self.seed), jnp.zeros((1,) + x.shape[1:])
            )
            self._apply = jax.jit(lambda a: net.apply(variables, a))
        return self._apply(x)


class FIDScorer:
    """Drop-in for the reference ``FIDScorer`` with a pluggable embed.

    ``embed_fn(x[B,H,W,C]) -> [B,D]``; defaults to the fixed random conv
    embedding (see module docstring for why).
    """

    def __init__(
        self,
        embed_fn: Callable | None = None,
        batch_size: int = 256,
    ):
        self.embed_fn = embed_fn or _RandomConvEmbed()
        self.batch_size = batch_size

    def _features(self, images) -> np.ndarray:
        feats = []
        n = images.shape[0]
        for s in range(0, n, self.batch_size):
            feats.append(
                np.asarray(self.embed_fn(jnp.asarray(images[s:s + self.batch_size])))
            )
        return np.concatenate(feats)

    def calculate_fid(self, images_real, images_fake) -> float:
        """(reference ``calculate_fid``, logged each round by
        ``fedgdkd/server.py:144-154``)."""
        mu1, s1 = activation_statistics(self._features(images_real))
        mu2, s2 = activation_statistics(self._features(images_fake))
        return frechet_distance(mu1, s1, mu2, s2)


class TorchScriptEmbed:
    """Real-InceptionV3 (or any) feature extractor from a TorchScript file.

    The reference scores FID with torchvision's pretrained InceptionV3
    (``FID/InceptionV3.py``); its weights are not shipped offline. When a
    scripted module IS available on disk (e.g. exported once with
    ``torch.jit.script(torchvision...inception_v3(...))``), this hook runs
    it on CPU via ``torch.jit.load`` — no torchvision dependency — making
    the resulting FID numbers comparable to published values.

    Input convention: NHWC float in [0, 1]; converted to NCHW, resized by
    nearest-neighbor to ``input_hw``, grayscale replicated to 3 channels.
    """

    def __init__(self, path: str, input_hw: int = 299):
        import torch

        self.torch = torch
        self.module = torch.jit.load(path, map_location="cpu").eval()
        self.input_hw = input_hw

    def __call__(self, x) -> np.ndarray:
        torch = self.torch
        arr = np.asarray(x, np.float32)
        if arr.shape[-1] == 1:
            arr = np.repeat(arr, 3, axis=-1)
        t = torch.from_numpy(np.transpose(arr, (0, 3, 1, 2)))
        if t.shape[-1] != self.input_hw:
            t = torch.nn.functional.interpolate(
                t, size=(self.input_hw, self.input_hw), mode="nearest"
            )
        with torch.no_grad():
            out = self.module(t)
        if isinstance(out, (tuple, list)):
            out = out[0]
        out = out.reshape(out.shape[0], -1)
        return out.numpy()


def make_fid_scorer(
    inception_path: str | None = None, batch_size: int = 64
) -> FIDScorer:
    """FIDScorer factory: uses the real (TorchScript) Inception embed when a
    weights file is present, otherwise the offline random-projection embed.
    ``inception_path`` defaults to ``$FEDML_TPU_INCEPTION`` if set."""
    import os

    path = inception_path or os.environ.get("FEDML_TPU_INCEPTION")
    if path:
        if not os.path.exists(path):
            # an explicitly requested extractor that is missing must NOT
            # silently degrade to the offline embed — the numbers would
            # look comparable to published FID but not be
            raise FileNotFoundError(
                f"Inception TorchScript file not found: {path}"
            )
        return FIDScorer(embed_fn=TorchScriptEmbed(path),
                         batch_size=batch_size)
    return FIDScorer(batch_size=batch_size)

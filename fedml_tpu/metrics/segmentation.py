"""Segmentation metrics: confusion-matrix evaluator.

Port of the reference ``Evaluator``
(``fedml_api/distributed/fedseg/utils.py:246-288``): pixel accuracy,
per-class accuracy, mean IoU, frequency-weighted IoU — all derived from one
[K, K] confusion matrix. The matrix accumulation is a jitted bincount on
device; metric finalization is host-side numpy (tiny)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def confusion_matrix_batch(gt, pred, num_classes: int) -> jnp.ndarray:
    """[K, K] counts for one batch; rows = ground truth, cols = prediction
    (reference ``_generate_matrix``, ``utils.py:276-281``). Pixels with
    labels outside [0, K) are ignored."""
    gt = gt.reshape(-1)
    pred = pred.reshape(-1)
    valid = (gt >= 0) & (gt < num_classes)
    label = jnp.where(valid, num_classes * gt + pred, num_classes * num_classes)
    counts = jnp.bincount(label, length=num_classes * num_classes + 1)
    return counts[:-1].reshape(num_classes, num_classes)


class SegEvaluator:
    """Stateful accumulator mirroring the reference API (``add_batch`` /
    metric getters / ``reset``)."""

    def __init__(self, num_classes: int):
        self.num_classes = num_classes
        self._cm_fn = jax.jit(
            lambda g, p: confusion_matrix_batch(g, p, num_classes)
        )
        self.reset()

    def reset(self):
        self.confusion = np.zeros((self.num_classes, self.num_classes))

    def add_batch(self, gt, pred):
        self.confusion += np.asarray(self._cm_fn(gt, pred))

    def pixel_accuracy(self) -> float:
        return float(np.diag(self.confusion).sum() / self.confusion.sum())

    def pixel_accuracy_class(self) -> float:
        with np.errstate(invalid="ignore", divide="ignore"):
            acc = np.diag(self.confusion) / self.confusion.sum(axis=1)
        return float(np.nanmean(acc))

    def mean_iou(self) -> float:
        with np.errstate(invalid="ignore", divide="ignore"):
            iou = np.diag(self.confusion) / (
                self.confusion.sum(axis=1)
                + self.confusion.sum(axis=0)
                - np.diag(self.confusion)
            )
        return float(np.nanmean(iou))

    def fw_iou(self) -> float:
        freq = self.confusion.sum(axis=1) / self.confusion.sum()
        with np.errstate(invalid="ignore", divide="ignore"):
            iou = np.diag(self.confusion) / (
                self.confusion.sum(axis=1)
                + self.confusion.sum(axis=0)
                - np.diag(self.confusion)
            )
        return float((freq[freq > 0] * iou[freq > 0]).sum())

"""Metrics sink: wandb-shaped logging without wandb.

The reference logs everything to wandb (``wandb.log({...})`` throughout, and
CI reads ``wandb-summary.json``; SURVEY.md §5.5). This sink provides the same
two artifacts — a step log and a latest-value summary — as JSONL + dict:
``close()`` materializes the summary as ``summary.json`` next to the
JSONL (the wandb-summary file the reference CI reads), and can forward
to wandb when it's importable.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any


def _json_default(v):
    """Serialize best-effort: floats where possible, ``repr`` otherwise
    — a single exotic value (an array, an exception, a config object)
    must not crash the whole metrics stream."""
    try:
        return float(v)
    except (TypeError, ValueError):
        return repr(v)


class MetricsSink:
    def __init__(self, path: str | None = None, use_wandb: bool = False):
        self.history: list[dict[str, Any]] = []
        self.summary: dict[str, Any] = {}
        self.path = path
        self._fh = None
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a")
        self._wandb = None
        if use_wandb:
            try:
                import wandb  # type: ignore

                self._wandb = wandb
            except ImportError:
                pass

    def log(self, record: dict[str, Any]) -> None:
        record = dict(record, _ts=time.time())
        self.history.append(record)
        self.summary.update(
            {k: v for k, v in record.items() if not k.startswith("_")}
        )
        if self._fh:
            self._fh.write(json.dumps(record, default=_json_default) + "\n")
            self._fh.flush()
        if self._wandb is not None and self._wandb.run is not None:
            self._wandb.log(record)

    def close(self) -> None:
        if self.path:
            # the wandb-summary artifact (latest value per key), written
            # next to the JSONL so CI can read one small file. When the
            # telemetry registry is live, its histograms ride along
            # with their p50/p95/p99 (bucket-interpolated — see
            # telemetry.percentiles_from_histogram for the error
            # bound), so a run summary carries the round-latency SLO
            # percentiles without a separate artifact.
            summary = dict(self.summary)
            try:
                from fedml_tpu.core import telemetry

                if telemetry.METRICS.enabled:
                    hists = telemetry.METRICS.snapshot()["histograms"]
                    if hists:
                        keep = ("count", "sum", "min", "max",
                                "p50", "p95", "p99")
                        summary["telemetry_histograms"] = {
                            name: {k: h[k] for k in keep if k in h}
                            for name, h in hists.items()
                        }
            except Exception:
                pass  # the summary must never die on telemetry state
            spath = os.path.join(
                os.path.dirname(self.path) or ".", "summary.json"
            )
            with open(spath, "w") as f:
                json.dump(summary, f, indent=2,
                          default=_json_default)
        if self._fh:
            self._fh.close()
            self._fh = None

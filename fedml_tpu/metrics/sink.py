"""Metrics sink: wandb-shaped logging without wandb.

The reference logs everything to wandb (``wandb.log({...})`` throughout, and
CI reads ``wandb-summary.json``; SURVEY.md §5.5). This sink provides the same
two artifacts — a step log and a latest-value summary — as JSONL + dict, and
can forward to wandb when it's importable.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any


class MetricsSink:
    def __init__(self, path: str | None = None, use_wandb: bool = False):
        self.history: list[dict[str, Any]] = []
        self.summary: dict[str, Any] = {}
        self.path = path
        self._fh = None
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a")
        self._wandb = None
        if use_wandb:
            try:
                import wandb  # type: ignore

                self._wandb = wandb
            except ImportError:
                pass

    def log(self, record: dict[str, Any]) -> None:
        record = dict(record, _ts=time.time())
        self.history.append(record)
        self.summary.update(
            {k: v for k, v in record.items() if not k.startswith("_")}
        )
        if self._fh:
            self._fh.write(json.dumps(record, default=float) + "\n")
            self._fh.flush()
        if self._wandb is not None and self._wandb.run is not None:
            self._wandb.log(record)

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None

"""Per-client observability: confusion matrices, per-client metrics, label
distributions.

Reference: ``fedml_api/standalone/utils/HeterogeneousModelBaseTrainerAPI.py``
— ``_local_test_on_all_clients`` (``:82-164``) logs
``Client {i}/Train|Test/Acc|Loss`` per round plus aggregate Train/Test
metrics; ``BaseClient.local_test`` builds per-client confusion matrices
(``BaseClient.py:60-73``, wandb heatmaps); ``_plot_client_label_
distributions`` (``:198-215``) logs per-client class-count tables.

TPU formulation: all per-client evaluation is ONE jitted vmap over the
padded per-client index maps (no per-client python eval loops); confusion
matrices are one-hot outer products reduced on device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.data.federated import FederatedArrays


def confusion_matrix(pred, y, num_classes: int, w=None) -> jax.Array:
    """[K, K] counts, rows = true label, cols = prediction."""
    if w is None:
        w = jnp.ones(y.shape[0])
    t = jax.nn.one_hot(y, num_classes) * w[:, None]
    p = jax.nn.one_hot(pred, num_classes)
    return t.T @ p


def _one_client_eval(model, num_classes: int, batch_size: int):
    """``(variables, x, y, idx_row, mask_row) -> {acc, loss, confusion,
    count}`` for one client's (padded) slice — pure, vmappable."""

    def one_client(variables, x, y, idx_row, mask_row):
        m = idx_row.shape[0]
        pad = (-m) % batch_size
        idx_p = jnp.concatenate([idx_row, jnp.zeros((pad,), idx_row.dtype)])
        w_p = jnp.concatenate([mask_row, jnp.zeros((pad,))])
        nb = (m + pad) // batch_size

        def body(carry, s):
            loss_sum, correct, cm = carry
            take = jax.lax.dynamic_slice_in_dim(
                idx_p, s * batch_size, batch_size
            )
            wb = jax.lax.dynamic_slice_in_dim(w_p, s * batch_size, batch_size)
            xb = jnp.take(x, take, axis=0)
            yb = jnp.take(y, take, axis=0)
            logits = model.apply_eval(variables, xb)
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, yb)
            pred = jnp.argmax(logits, -1)
            loss_sum = loss_sum + jnp.sum(ce * wb)
            correct = correct + jnp.sum((pred == yb) * wb)
            cm = cm + confusion_matrix(pred, yb, num_classes, wb)
            return (loss_sum, correct, cm), None

        init = (
            jnp.asarray(0.0),
            jnp.asarray(0.0),
            jnp.zeros((num_classes, num_classes)),
        )
        (loss_sum, correct, cm), _ = jax.lax.scan(body, init, jnp.arange(nb))
        n = jnp.sum(mask_row)
        denom = jnp.maximum(n, 1.0)
        return {
            "acc": correct / denom,
            "loss": loss_sum / denom,
            "confusion": cm,
            "count": n,
        }

    return one_client


# bounded LRU: keeps recent evaluators' compiled executables alive without
# pinning every model a long sweep ever evaluated
_EVAL_CACHE: "dict" = {}
_EVAL_CACHE_MAX = 8


def build_per_client_eval(
    model, num_classes: int, batch_size: int = 256, stacked: bool = False
):
    """Jitted ``(variables, x, y, idx[N,M], mask[N,M]) ->
    {acc[N], loss[N], confusion[N,K,K], count[N]}`` — every client's local
    test in one compiled vmap (replaces the reference's per-client
    ``local_test`` python loop). ``stacked=True`` maps the variables'
    leading client axis too (per-client personalized models).

    Memoized per (model, num_classes, batch_size, stacked) so per-round
    logging reuses one compiled evaluator instead of re-jitting a fresh
    closure every call."""
    key = (id(model), num_classes, batch_size, stacked)
    fn = _EVAL_CACHE.pop(key, None)
    if fn is None:
        one = _one_client_eval(model, num_classes, batch_size)
        in_axes = (
            (0, None, None, 0, 0) if stacked else (None, None, None, 0, 0)
        )
        fn = jax.jit(jax.vmap(one, in_axes=in_axes))
    _EVAL_CACHE[key] = fn  # re-insert = most recently used
    while len(_EVAL_CACHE) > _EVAL_CACHE_MAX:
        _EVAL_CACHE.pop(next(iter(_EVAL_CACHE)))
    return fn


def label_distribution(arrays: FederatedArrays) -> np.ndarray:
    """[N, K] per-client class counts (reference
    ``_plot_client_label_distributions``)."""
    y = np.asarray(arrays.y)
    if y.ndim > 1:  # multi-hot tasks: sum label mass per class
        return np.stack(
            [
                (np.asarray(arrays.mask[i])[:, None]
                 * y[np.asarray(arrays.idx[i])]).sum(0)
                for i in range(arrays.num_clients)
            ]
        )
    k = arrays.num_classes
    out = np.zeros((arrays.num_clients, k))
    for i in range(arrays.num_clients):
        rows = np.asarray(arrays.idx[i])[np.asarray(arrays.mask[i]) > 0]
        out[i] = np.bincount(y[rows], minlength=k)[:k]
    return out


def log_per_client_observability(
    sink,
    model,
    variables,
    arrays: FederatedArrays,
    round_idx: int,
    prefix: str = "",
    include_confusion: bool = True,
    stacked: bool = False,
):
    """Evaluate every client's train + test slice and write reference-shaped
    records into the sink: ``Client {i}/Train|Test/Acc|Loss`` scalars plus
    (optionally) per-client test confusion matrices and the
    label-distribution table (nested lists — the JSONL analog of the
    reference's wandb heatmaps/tables).

    ``stacked=True``: ``variables`` carries a leading client axis
    (personalized models, e.g. hetero buckets); otherwise one global model
    is evaluated on every client's slices."""
    ev = build_per_client_eval(model, arrays.num_classes, stacked=stacked)
    train = ev(variables, arrays.x, arrays.y, arrays.idx, arrays.mask)
    test = ev(variables, arrays.test_x, arrays.test_y, arrays.test_idx,
              arrays.test_mask)

    record: dict = {"round": round_idx}
    for i in range(arrays.num_clients):
        record[f"{prefix}Client {i}/Train/Acc"] = float(train["acc"][i])
        record[f"{prefix}Client {i}/Train/Loss"] = float(train["loss"][i])
        record[f"{prefix}Client {i}/Test/Acc"] = float(test["acc"][i])
        record[f"{prefix}Client {i}/Test/Loss"] = float(test["loss"][i])
    # aggregates weighted by true sample counts (reference sums
    # num_correct / num_samples across clients, :137-141)
    tc = np.asarray(train["count"])
    vc = np.asarray(test["count"])
    record[f"{prefix}Train/Acc"] = float(
        np.sum(np.asarray(train["acc"]) * tc) / max(tc.sum(), 1.0)
    )
    record[f"{prefix}Test/Acc"] = float(
        np.sum(np.asarray(test["acc"]) * vc) / max(vc.sum(), 1.0)
    )
    if include_confusion:
        record[f"{prefix}confusion_test"] = np.asarray(
            test["confusion"]
        ).tolist()
    record[f"{prefix}label_distribution"] = label_distribution(
        arrays
    ).tolist()
    sink.log(record)
    return record

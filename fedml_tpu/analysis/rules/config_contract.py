"""parse-time-validation: the config<->CLI contract, checked statically.

The historical bug class: the fednova+defense crash-loop — a config
combination rejected only at first round close, where a supervised
server burns its restart budget crash-looping, instead of at
``parse_args`` where the operator sees one clear error (PR 4's second
review round moved it; this rule keeps it moved). Three checks:

- **field->flag**: every ``FedConfig``/``DeployConfig`` field that is
  READ anywhere in the run paths must have a registered CLI flag
  (``--<field>`` or a declared alias in ``fedlint.json``
  ``options.parse-time-validation.flag_aliases``) — a field reachable
  only by hand-editing a config JSON is validated nowhere;
- **duplicate registration**: the same option string registered twice
  in one parser build;
- **reserved flags**: option strings owned by the run CLI
  (``options.parse-time-validation.reserved_flags``, the runtime twin
  is ``fedml_tpu.analysis.flags.check_flag_registry``) registered by
  any other module — bench.py minting its own ``--slo`` would shadow
  the SloSpec semantics operators rely on.
"""

from __future__ import annotations

import ast
from typing import Iterator

from fedml_tpu.analysis.core import Finding, Project, register_rule

_RULE = "parse-time-validation"
_DEFAULT_CLASSES = ("FedConfig", "DeployConfig")


@register_rule(
    _RULE,
    "config fields read in run paths need a registered CLI flag; "
    "duplicate and reserved-flag registrations fail at lint time",
)
def check(project: Project) -> Iterator[Finding]:
    opts = project.config.options.get(_RULE, {})
    classes = tuple(opts.get("config_classes", _DEFAULT_CLASSES))
    aliases: dict[str, str] = dict(opts.get("flag_aliases", {}))
    reserved = set(opts.get("reserved_flags", ()))
    owner = opts.get("reserved_owner", "")

    # --- collect dataclass fields ------------------------------------
    fields: list[tuple[str, str, str, int]] = []  # (cls, field, path, ln)
    for relpath, mod in sorted(project.modules.items()):
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef) and node.name in classes:
                for stmt in node.body:
                    if isinstance(stmt, ast.AnnAssign) \
                            and isinstance(stmt.target, ast.Name):
                        fields.append((node.name, stmt.target.id,
                                       mod.relpath, stmt.lineno))

    # --- collect flags + duplicates + reserved misuse ----------------
    flags: set[str] = set()
    for relpath, mod in sorted(project.modules.items()):
        per_scope: dict[str, dict[str, int]] = {}
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add_argument"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value.startswith("--")):
                continue
            flag = node.args[0].value
            flags.add(flag)
            scope = mod.enclosing_function(node.lineno)
            seen = per_scope.setdefault(scope, {})
            if flag in seen:
                # no line numbers in the message: it feeds the
                # baseline fingerprint, which must survive line drift
                yield Finding(
                    rule=_RULE, path=mod.relpath, line=node.lineno,
                    scope=scope,
                    message=(
                        f"flag `{flag}` registered twice in one "
                        f"parser"
                    ),
                )
            else:
                seen[flag] = node.lineno
            if flag in reserved and mod.relpath != owner:
                yield Finding(
                    rule=_RULE, path=mod.relpath, line=node.lineno,
                    scope=scope,
                    message=(
                        f"reserved flag `{flag}` belongs to {owner} "
                        f"(the run CLI's SLO/export plane) — rename "
                        f"this flag"
                    ),
                )

    if not flags:
        return  # no CLI in the analyzed tree: field->flag is vacuous

    # --- field reads -------------------------------------------------
    read_attrs: set[str] = set()
    for relpath, mod in sorted(project.modules.items()):
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load):
                read_attrs.add(node.attr)

    for cls, field, relpath, lineno in fields:
        if field not in read_attrs:
            continue  # never read: not this rule's concern
        flag = aliases.get(field, field)
        if flag == "":  # alias to "" = explicitly flagless by policy
            continue
        candidates = {f"--{flag}", f"--no_{flag}", f"--no-{flag}"}
        if not candidates & flags:
            yield Finding(
                rule=_RULE, path=relpath, line=lineno, scope=cls,
                message=(
                    f"{cls}.{field} is read in run paths but has no "
                    f"registered CLI flag (--{flag}) — it can only be "
                    f"set by hand-editing config JSON, bypassing "
                    f"parse-time validation"
                ),
            )

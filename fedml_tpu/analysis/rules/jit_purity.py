"""jit-purity: no host-impure work inside jit-reachable functions.

The historical bug class: a ``time.time()`` or ``print`` inside a
traced round body executes ONCE at trace time and never again (the
metric silently freezes), ``random``/``np.random`` draws bake one
sample into the executable (every round reuses it — the adversary
injection and cohort sampling bugs PR 4/5 reviews hunted by hand), and
``.item()`` / ``float()`` coercion forces a device sync in the middle
of a compiled region. ``jax.random`` / ``jax.debug.print`` are the
sanctioned replacements.
"""

from __future__ import annotations

import ast
from typing import Iterator

from fedml_tpu.analysis.core import Finding, Project, register_rule
from fedml_tpu.analysis.rules._common import (
    dotted_base, fn_scope, own_walk, resolve_module,
)
from fedml_tpu.analysis.rules.traced_branch import (
    _is_static, _propagate,
)

#: modules whose every call is host-impure under trace
IMPURE_MODULES = ("time", "random", "subprocess", "numpy.random",
                  "socket")
#: bare builtins that are host-impure under trace
IMPURE_BUILTINS = {"print", "open", "input"}
#: method calls that force a device->host sync on a traced value
SYNC_METHODS = {"item", "tolist", "block_until_ready"}


@register_rule(
    "jit-purity",
    "host-impure calls (time/random/np.random/IO/print/.item()/float "
    "coercion) inside functions reachable from a jit compile site",
)
def check(project: Project) -> Iterator[Finding]:
    for qual in sorted(project.jit_reachable):
        fi = project.functions.get(qual)
        if fi is None or isinstance(fi.node, ast.Lambda):
            continue
        mod = fi.module
        scope = fn_scope(fi)
        # taint set for the sync-coercion checks: parameters are traced
        # (conservatively — this IS a jit-reachable function), values
        # derived only from shapes/dtypes/len() are not, so
        # `int(x.shape[0] * f)` stays legal while `float(loss)` flags
        args = fi.node.args
        params = [a.arg for a in (args.posonlyargs + args.args
                                  + args.kwonlyargs)]
        if params and params[0] in ("self", "cls"):
            params = params[1:]
        traced = _propagate(fi.node, set(params))
        for node in own_walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name):
                if f.id in IMPURE_BUILTINS:
                    yield _finding(mod, node, scope,
                                   f"host-impure call `{f.id}(...)`")
                elif f.id in ("float", "int") and node.args \
                        and not _is_static(node.args[0], traced):
                    yield _finding(
                        mod, node, scope,
                        f"`{f.id}(...)` coercion forces a host sync on "
                        f"a traced value",
                    )
                else:
                    full = resolve_module(mod, f.id) or ""
                    if _impure_module(full):
                        yield _finding(mod, node, scope,
                                       f"host-impure call `{full}`")
            elif isinstance(f, ast.Attribute):
                if f.attr in SYNC_METHODS \
                        and not _is_static(f.value, traced):
                    yield _finding(
                        mod, node, scope,
                        f"`.{f.attr}()` forces a host sync on a traced "
                        f"value",
                    )
                    continue
                dotted = dotted_base(f)
                full = resolve_module(mod, dotted)
                if full is not None and _impure_module(full):
                    yield _finding(mod, node, scope,
                                   f"host-impure call `{full}.{f.attr}`")


def _impure_module(full: str) -> bool:
    return any(full == m or full.startswith(m + ".")
               for m in IMPURE_MODULES)


def _finding(mod, node, scope: str, what: str) -> Finding:
    return Finding(
        rule="jit-purity", path=mod.relpath, line=node.lineno,
        scope=scope,
        message=f"{what} in jit-reachable `{scope}`",
    )

"""metric-vocabulary: code and docs/OBSERVABILITY.md must agree,
bidirectionally.

The observability plane's contract (PR 2 onward) is that every metric
name is a documented vocabulary row — operators alert on names, and an
undocumented name is invisible to them (the historical instances this
PR fixes: ``perf.profile.window_s`` and ``recovery.rejoins_reconciled``
were written by the runtime but absent from the tables). The rule
parses every ``| name | kind | meaning |`` table in the vocabulary doc
into patterns (``<...>`` placeholders become wildcards, ``{a,b}``
braces and ``a/b`` slash-runs expand) and checks both directions:

- every string literal (or f-string/concat literal PREFIX) passed to
  ``inc``/``gauge``/``observe``/``gauge_labeled``/``labeled_name``/
  ``merge_histogram`` on a metrics registry must match a documented
  family;
- every documented family must have at least one write site in the
  analyzed code (a stale table row is a lie operators will alert on) —
  families written by infrastructure the analyzer cannot see through
  are declared in ``fedlint.json`` ``options.metric-vocabulary.
  assume_written``.

The doc->code direction is only meaningful when the scan actually
covers the runtime: linting a subtree (``fedlint scripts/``) must not
indict every row whose writer lives elsewhere. Default gating
(``options.metric-vocabulary.reverse: "auto"``): the stale-row checks
run when the analyzed modules include the metrics-registry
implementation (a ``class MetricsRegistry`` definition — scanning the
telemetry spine means scanning the runtime). ``"always"``/``"never"``
override.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterator

from fedml_tpu.analysis.core import Finding, Project, register_rule
from fedml_tpu.analysis.rules._common import static_name_prefix

_RULE = "metric-vocabulary"
_WRITE_METHODS = {"inc", "gauge", "observe", "gauge_labeled",
                  "labeled_name", "merge_histogram"}
_HEADER_RE = re.compile(r"^\|\s*name\s*\|\s*kind\s*\|", re.IGNORECASE)
_TOKEN_RE = re.compile(r"`([^`]+)`")


class _Pattern:
    def __init__(self, raw: str, line: int):
        self.raw = raw
        self.line = line
        self.literal_prefix = raw.split("<", 1)[0]
        self.has_wildcard = "<" in raw
        rx = "".join(
            ".+" if part.startswith("<") else re.escape(part)
            for part in re.split(r"(<[^>]*>)", raw)
        )
        self.regex = re.compile(rx + r"\Z")
        self.satisfied = False

    def matches_exact(self, name: str) -> bool:
        return self.regex.match(name) is not None

    def matches_prefix(self, prefix: str) -> bool:
        """A dynamic write with literal head ``prefix`` may produce a
        name of this family — but only when the head ends at a FAMILY
        BOUNDARY (a ``.``): without that, ``f"rec{kind}"`` would
        satisfy `recovery.resumes` and one sloppy ``f"perf.{x}"``
        write would mark every perf row written."""
        if self.has_wildcard:
            lit = self.literal_prefix
            if prefix.startswith(lit):
                return True  # head reaches into the wildcard
            return lit.startswith(prefix) and _boundary(lit, prefix)
        return self.raw.startswith(prefix) \
            and _boundary(self.raw, prefix)


def _boundary(longer: str, prefix: str) -> bool:
    """True when ``prefix`` ends at a dotted-name boundary of
    ``longer`` (equal, ends with '.', or the next char is '.')."""
    return len(longer) == len(prefix) or prefix.endswith(".") \
        or longer[len(prefix)] == "."


def _expand_cell(cell: str, line: int) -> list[_Pattern]:
    out: list[_Pattern] = []
    for token in _TOKEN_RE.findall(cell):
        for name in _expand_token(token):
            out.append(_Pattern(name, line))
    return out


def _expand_token(token: str) -> list[str]:
    # slash-run alternation: "chaos.dropped/delayed/..." — the first
    # element carries the dotted prefix the rest inherit
    if "/" in token:
        parts = token.split("/")
        head = parts[0]
        prefix = head[: head.rfind(".") + 1] if "." in head else ""
        expanded = [head] + [prefix + p for p in parts[1:]]
        return [n for p in expanded for n in _expand_token(p)] \
            if "{" in token else expanded
    # brace alternation: "perf.profile.{compute,idle}_frac"
    m = re.search(r"\{([^{}]*)\}", token)
    if m:
        out = []
        for alt in m.group(1).split(","):
            out.extend(_expand_token(token[: m.start()] + alt
                                     + token[m.end():]))
        return out
    return [token]


def _scope_covers_runtime(project: Project) -> bool:
    """True when the scan includes the metrics-registry implementation
    — the sentinel that the runtime (and so the writers the doc rows
    describe) is actually inside the analyzed tree."""
    for mod in project.modules.values():
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef) \
                    and node.name == "MetricsRegistry":
                return True
    return False


def _load_vocabulary(project: Project) -> tuple[str, list[_Pattern]]:
    doc_rel = project.config.vocabulary_doc
    doc_path = os.path.join(project.root, doc_rel)
    patterns: list[_Pattern] = []
    if not os.path.exists(doc_path):
        return doc_rel, patterns
    with open(doc_path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    in_table = False
    for i, line in enumerate(lines, start=1):
        stripped = line.strip()
        if _HEADER_RE.match(stripped):
            in_table = True
            continue
        if in_table:
            if not stripped.startswith("|"):
                in_table = False
                continue
            if set(stripped) <= {"|", "-", " "}:
                continue  # the |---|---| separator
            cell = stripped.strip("|").split("|", 1)[0]
            patterns.extend(_expand_cell(cell, i))
    return doc_rel.replace(os.sep, "/"), patterns


def _iter_metric_writes(project: Project):
    """Yield ``(mod, call, name_or_prefix, is_exact, scope)`` for every
    registry write whose name has a statically-known part."""
    for relpath, mod in sorted(project.modules.items()):
        registry_locals = _registry_locals(mod)
        helpers = _name_helpers(mod)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute) \
                    or node.func.attr not in _WRITE_METHODS \
                    or not node.args:
                continue
            base = node.func.value
            base_text = ast.unparse(base)
            low = base_text.lower()
            if not (base_text.endswith("METRICS")
                    or base_text in registry_locals
                    or "registry" in low or "metrics" in low):
                continue
            name, exact = static_name_prefix(node.args[0])
            if name is None:
                # a helper call returning an f-string name
                # (`m.inc(_bytes_by_type_metric(t), n)`) contributes
                # the helper's literal prefix
                arg = node.args[0]
                if isinstance(arg, ast.Call) \
                        and isinstance(arg.func, ast.Name) \
                        and arg.func.id in helpers:
                    name, exact = helpers[arg.func.id]
                else:
                    continue
            if node.func.attr in ("gauge_labeled", "labeled_name"):
                # the written name is family + sep + label
                name, exact = name + ".", False
            scope = mod.enclosing_function(node.lineno)
            yield mod, node, name, exact, scope


def _registry_locals(mod) -> set[str]:
    """Names bound from a registry value (``m = telemetry.METRICS``,
    ``m = self._registry``, ``m = registry or telemetry.METRICS``)."""
    out: set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign):
            text = ast.unparse(node.value).lower()
            if text.endswith("metrics") or "registry" in text \
                    or "metrics" in text:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


def _name_helpers(mod) -> dict[str, tuple[str, bool]]:
    """Module functions that produce a metric name with a literal
    dotted prefix — base.py's ``_bytes_by_type_metric`` idiom (the
    f-string may be cached through a dict, so every string-producing
    expression in the body is considered; the helper qualifies when
    they all agree on ONE prefix)."""
    out: dict[str, tuple[str, bool]] = {}
    for qual, fi in mod.functions.items():
        node = fi.node
        if isinstance(node, ast.Lambda) or fi.cls is not None:
            continue
        prefixes: dict[str, bool] = {}
        for sub in ast.walk(node):
            if isinstance(sub, (ast.JoinedStr, ast.Constant)):
                name, exact = static_name_prefix(sub)
                if name is not None and "." in name \
                        and re.fullmatch(r"[a-z_][a-zA-Z0-9_.]*",
                                         name):
                    # a JoinedStr and its own inner Constant both
                    # surface; the prefix (non-exact) claim wins
                    prefixes[name] = prefixes.get(name, True) and exact
        if len(prefixes) == 1:
            name, exact = next(iter(prefixes.items()))
            out[fi.name] = (name, exact)
    return out


@register_rule(
    _RULE,
    "every metric written to the registry must match a documented "
    "vocabulary row in docs/OBSERVABILITY.md, and every documented "
    "row must have a write site (bidirectional, prefix-wildcard "
    "families supported)",
)
def check(project: Project) -> Iterator[Finding]:
    doc_rel, patterns = _load_vocabulary(project)
    if not patterns:
        return  # no vocabulary doc in this tree: nothing to check
    opts = project.config.options.get(_RULE, {})
    assume = set(opts.get("assume_written", ()))
    for pat in patterns:
        if any(pat.matches_exact(a) or a == pat.raw for a in assume):
            pat.satisfied = True

    for mod, node, name, exact, scope in _iter_metric_writes(project):
        hit = False
        for pat in patterns:
            ok = pat.matches_exact(name) if exact \
                else pat.matches_prefix(name)
            if ok:
                pat.satisfied = True
                hit = True
        if not hit:
            shown = name if exact else f"{name}*"
            yield Finding(
                rule=_RULE, path=mod.relpath, line=node.lineno,
                scope=scope,
                message=(
                    f"metric `{shown}` is not in the "
                    f"{doc_rel} vocabulary tables — add a row or "
                    f"rename to a documented family"
                ),
            )

    reverse = opts.get("reverse", "auto")
    if reverse == "never" or (reverse == "auto"
                              and not _scope_covers_runtime(project)):
        return
    for pat in patterns:
        if not pat.satisfied:
            yield Finding(
                rule=_RULE, path=doc_rel, line=pat.line,
                scope="<vocabulary>",
                message=(
                    f"documented metric family `{pat.raw}` has no "
                    f"write site in the analyzed code — stale row, or "
                    f"add it to options.metric-vocabulary."
                    f"assume_written"
                ),
            )

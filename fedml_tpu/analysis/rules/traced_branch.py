"""traced-branch: Python control flow on traced values.

A Python ``if``/``while``/``assert`` over a value derived from a traced
parameter raises ``TracerBoolConversionError`` at best; at worst (when
the branch happens to be concretizable at trace time) it bakes ONE
branch into the executable — the class of bug PR 5's elastic masking
review kept finding (``lax.cond`` / ``jnp.where`` / ``lax.while_loop``
are the traced forms). Checked on DIRECT jit roots, where the
parameter<->tracer correspondence is known exactly: parameters minus
the call site's ``static_argnums``/``static_argnames`` are traced, and
taint propagates through straight-line assignments.

Shape/dtype/identity tests stay legal: ``x.shape``/``x.ndim``/
``x.dtype``/``x.size``, ``len(x)``, ``isinstance(x, ...)`` and
``is (not) None`` comparisons are static under trace.
"""

from __future__ import annotations

import ast
from typing import Iterator

from fedml_tpu.analysis.core import Finding, Project, register_rule
from fedml_tpu.analysis.rules._common import own_walk

_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
_STATIC_CALLS = {"len", "isinstance", "hasattr", "getattr", "type"}


@register_rule(
    "traced-branch",
    "Python if/while/assert on values derived from traced parameters "
    "of a function compiled by jax.jit/ProgramSite/shard_map",
)
def check(project: Project) -> Iterator[Finding]:
    for qual, static_names in sorted(project.jit_roots.items()):
        fi = project.functions.get(qual)
        if fi is None or isinstance(fi.node, ast.Lambda):
            continue
        node = fi.node
        params = [a.arg for a in (node.args.posonlyargs + node.args.args
                                  + node.args.kwonlyargs)]
        if params and params[0] in ("self", "cls"):
            params = params[1:]
        traced = {p for p in params if p not in static_names}
        if not traced:
            continue
        traced = _propagate(node, traced)
        scope = qual.split(":", 1)[1]
        for sub in own_walk(node):
            test = None
            kind = None
            if isinstance(sub, (ast.If, ast.While)):
                test, kind = sub.test, type(sub).__name__.lower()
            elif isinstance(sub, ast.Assert):
                test, kind = sub.test, "assert"
            elif isinstance(sub, ast.IfExp):
                test, kind = sub.test, "conditional expression"
            if test is None or _is_static(test, traced):
                continue
            names = sorted(_traced_names(test, traced))
            yield Finding(
                rule="traced-branch", path=fi.module.relpath,
                line=sub.lineno, scope=scope,
                message=(
                    f"python {kind} on traced value(s) "
                    f"{', '.join(names)} in jit-compiled `{scope}` — "
                    f"use lax.cond/jnp.where/lax.while_loop"
                ),
            )


def _propagate(fn_node: ast.AST, traced: set[str]) -> set[str]:
    """Fixpoint taint propagation through assignments in the body."""
    for _ in range(10):
        grew = False
        for sub in own_walk(fn_node):
            targets = None
            value = None
            if isinstance(sub, ast.Assign):
                targets, value = sub.targets, sub.value
            elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
                targets, value = [sub.target], sub.value
            elif isinstance(sub, ast.AugAssign):
                targets, value = [sub.target], sub.value
            if value is None or _is_static(value, traced):
                continue
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name) and n.id not in traced:
                        traced.add(n.id)
                        grew = True
        if not grew:
            break
    return traced


def _traced_names(expr: ast.AST, traced: set[str]) -> set[str]:
    return {n.id for n in ast.walk(expr)
            if isinstance(n, ast.Name) and n.id in traced}


def _is_static(expr: ast.AST, traced: set[str]) -> bool:
    """True when the expression cannot carry traced DATA: constants,
    untraced names, shape/dtype attributes, len()/isinstance() calls,
    `is None` identity tests, and compositions thereof."""
    if isinstance(expr, ast.Constant):
        return True
    if isinstance(expr, ast.Name):
        return expr.id not in traced
    if isinstance(expr, ast.Attribute):
        return expr.attr in _STATIC_ATTRS or _is_static(expr.value,
                                                        traced)
    if isinstance(expr, ast.Call):
        fname = None
        if isinstance(expr.func, ast.Name):
            fname = expr.func.id
        elif isinstance(expr.func, ast.Attribute):
            fname = expr.func.attr
        if fname in _STATIC_CALLS:
            return True
        return all(_is_static(a, traced) for a in expr.args) and \
            _is_static(expr.func, traced)
    if isinstance(expr, ast.Compare):
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops):
            return True
        return _is_static(expr.left, traced) and all(
            _is_static(c, traced) for c in expr.comparators
        )
    if isinstance(expr, ast.BoolOp):
        return all(_is_static(v, traced) for v in expr.values)
    if isinstance(expr, ast.UnaryOp):
        return _is_static(expr.operand, traced)
    if isinstance(expr, ast.BinOp):
        return _is_static(expr.left, traced) and \
            _is_static(expr.right, traced)
    if isinstance(expr, ast.Subscript):
        return _is_static(expr.value, traced) and \
            _is_static(expr.slice, traced)
    if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
        return all(_is_static(e, traced) for e in expr.elts)
    # unknown expression kinds: only flag when a traced name is visibly
    # inside (conservative against false positives)
    return not _traced_names(expr, traced)

"""lock-hygiene: no blocking work while holding a lock, and no lock
acquisition-order cycles.

The historical bug class: the PR 11 time-series shutdown race (flush
joining the flusher while appends held the lock) and the PR 4
reputation race (admission on the dispatch thread vs the deadline
timer) were both "blocking work sneaked under a lock" defects found in
review. The rule flags calls that can block — socket send/recv/
connect, ``serialize``/``seal``, orbax ``save``, ``time.sleep``,
``subprocess`` invocations, thread ``.join()``, ``Event.wait()``,
manager sends — LEXICALLY inside a ``with <lock>:`` body, and builds a
lock-acquisition-order graph (edge A->B when B is taken while A is
held) flagging cycles.

Condition variables are exempt by name (``*_cv``/``*cond*``):
``cv.wait()`` RELEASES the lock — that is its contract, not a bug.
String ``sep.join(parts)`` is distinguished from thread joins by
argument shape (``str.join`` always takes the iterable; a zero-arg or
timeout-only ``.join()`` is a thread/process join).
"""

from __future__ import annotations

import ast
from typing import Iterator

from fedml_tpu.analysis.core import Finding, Project, register_rule
from fedml_tpu.analysis.rules._common import (
    dotted_base, fn_scope, resolve_module,
)

_RULE = "lock-hygiene"

#: terminal call names that can block the holder
BLOCKING = {
    "sleep", "sendall", "send", "recv", "accept", "connect",
    "create_connection", "serialize", "seal", "open_sealed", "save",
    "wait", "send_message", "broadcast", "urlopen",
}
_SUBPROCESS = {"run", "call", "check_call", "check_output", "Popen"}


def _lock_name(expr: ast.AST) -> str | None:
    """Identify a with-context as a lock by name; None for non-locks
    and for condition variables (whose wait() releases the lock)."""
    text = ast.unparse(expr)
    low = text.lower()
    if "_cv" in low or "cond" in low:
        return None
    if "lock" in low or "mutex" in low:
        # strip a .acquire-ish call / timeout decoration
        return text.split("(")[0] if text.endswith(")") else text
    return None


@register_rule(
    _RULE,
    "blocking calls lexically inside a `with <lock>:` body, plus "
    "lock-acquisition-order cycles across the project",
)
def check(project: Project) -> Iterator[Finding]:
    # acquisition-order graph over normalized lock ids
    order_edges: dict[tuple[str, str], tuple[str, int, str]] = {}
    for relpath, mod in sorted(project.modules.items()):
        for qual, fi in sorted(mod.functions.items()):
            if isinstance(fi.node, ast.Lambda):
                continue
            scope = fn_scope(fi)
            yield from _check_withs(mod, fi, scope, order_edges)
    yield from _report_cycles(order_edges)


def _check_withs(mod, fi, scope, order_edges) -> Iterator[Finding]:
    def walk(node, held: list[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(child, ast.With):
                locks = [
                    _normalize(mod, fi, _lock_name(i.context_expr))
                    for i in child.items
                    if _lock_name(i.context_expr) is not None
                ]
                for lock in locks:
                    for outer in held:
                        if outer != lock:
                            order_edges.setdefault(
                                (outer, lock),
                                (mod.relpath, child.lineno, scope),
                            )
                yield from walk(child, held + locks)
                continue
            if held and isinstance(child, ast.Call):
                found = _blocking_reason(mod, child)
                if found:
                    yield Finding(
                        rule=_RULE, path=mod.relpath,
                        line=child.lineno, scope=scope,
                        message=(
                            f"blocking call `{found}` while holding "
                            f"`{held[-1]}`"
                        ),
                    )
            yield from walk(child, held)

    yield from walk(fi.node, [])


def _normalize(mod, fi, lock_text: str | None) -> str:
    """`self._lock` -> "Cls._lock" so the order graph spans methods;
    bare names scope to the module."""
    if lock_text is None:
        return ""
    if lock_text.startswith("self.") and fi.cls:
        return f"{fi.cls}{lock_text[4:]}"
    if "." not in lock_text:
        return f"{mod.modname}:{lock_text}"
    return lock_text


def _blocking_reason(mod, call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Name):
        full = resolve_module(mod, f.id) or ""
        if full.startswith("time.sleep") or full == "subprocess.Popen":
            return full
        return None
    if not isinstance(f, ast.Attribute):
        return None
    base = dotted_base(f)
    full = resolve_module(mod, base) or (base or "")
    if f.attr in _SUBPROCESS and full.startswith("subprocess"):
        return f"subprocess.{f.attr}"
    if f.attr == "join":
        # str.join always takes the iterable; a 0-arg or timeout-only
        # join is a thread/process join
        if not call.args and not call.keywords:
            return f"{base or '<obj>'}.join"
        if call.keywords and all(k.arg == "timeout"
                                 for k in call.keywords):
            return f"{base or '<obj>'}.join"
        if len(call.args) == 1 and isinstance(call.args[0],
                                              ast.Constant) \
                and isinstance(call.args[0].value, (int, float)):
            return f"{base or '<obj>'}.join"
        return None
    if f.attr in BLOCKING:
        if f.attr == "sleep" and not (full.startswith("time")
                                      or base is None):
            return None
        if f.attr == "wait" and base is not None:
            # Condition.wait() RELEASES the lock — exempt receivers
            # that read as condition variables, matching the
            # with-context exemption
            low = base.lower()
            if "_cv" in low or "cond" in low:
                return None
        return f"{base + '.' if base else ''}{f.attr}"
    return None


def _report_cycles(order_edges) -> Iterator[Finding]:
    graph: dict[str, set[str]] = {}
    for (a, b) in order_edges:
        graph.setdefault(a, set()).add(b)
    seen_cycles: set[tuple[str, ...]] = set()
    for start in sorted(graph):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    cyc = tuple(sorted(path))
                    if cyc in seen_cycles:
                        continue
                    seen_cycles.add(cyc)
                    relpath, line, scope = order_edges[(node, start)]
                    yield Finding(
                        rule=_RULE, path=relpath, line=line,
                        scope=scope,
                        message=(
                            "lock acquisition-order cycle: "
                            + " -> ".join(path + [start])
                        ),
                    )
                elif nxt not in path and len(path) < 6:
                    stack.append((nxt, path + [nxt]))

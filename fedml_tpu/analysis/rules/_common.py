"""Shared AST helpers for fedlint rules."""

from __future__ import annotations

import ast
from typing import Iterator

from fedml_tpu.analysis.core import FunctionInfo, ModuleInfo


def own_walk(root: ast.AST) -> Iterator[ast.AST]:
    """Walk ``root`` WITHOUT descending into nested function/class
    bodies — each nested def is its own FunctionInfo and reports its
    own findings; double-reporting through the parent would make one
    defect two baseline entries."""
    stack = [root]
    first = True
    while stack:
        node = stack.pop()
        if not first and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                   ast.ClassDef)
        ):
            continue
        first = False
        yield node
        stack.extend(ast.iter_child_nodes(node))


def dotted_base(expr: ast.AST) -> str | None:
    """``np.random.normal`` -> "np.random"; ``time.time`` -> "time";
    bare names -> None."""
    parts: list[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return ".".join(parts[:-1]) if len(parts) > 1 else None
    return None


def resolve_module(mod: ModuleInfo, dotted: str | None) -> str | None:
    """Map a call's dotted base through the module's import aliases:
    with ``import numpy as np``, "np.random" -> "numpy.random"."""
    if not dotted:
        return None
    head, _, rest = dotted.partition(".")
    full = mod.import_aliases.get(head)
    if full is None:
        frm = mod.from_imports.get(head)
        if frm is None:
            return None
        full = frm
    return f"{full}.{rest}" if rest else full


def fn_scope(fi: FunctionInfo) -> str:
    return fi.qualname.split(":", 1)[1]


def static_name_prefix(arg: ast.AST) -> tuple[str | None, bool]:
    """The statically-known part of a metric-name expression:
    ``("name", True)`` for a full literal, ``("pre.", False)`` for an
    f-string / ``"pre." + x`` concatenation with a literal head,
    ``(None, False)`` when nothing is static."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value, True
    if isinstance(arg, ast.JoinedStr):
        if arg.values and isinstance(arg.values[0], ast.Constant):
            return str(arg.values[0].value), False
        return None, False
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add):
        left, full = static_name_prefix(arg.left)
        if left is not None:
            return left, False
        return None, False
    return None, False

"""recompile-hazard: jit programs that silently recompile.

Two shapes the elastic work (PR 5) and round fusion (PR 10) exist to
prevent:

- a raw compile site (``jax.jit``/``ProgramSite``/``shard_map``)
  invoked LEXICALLY inside a loop body — every iteration traces and
  compiles a fresh executable (``CompiledRoundCache`` is exempt: being
  called per round while caching per bucket is its whole point);
- a nested function or lambda handed to a compile site while closing
  over a visibly-mutable enclosing value (a name bound to a
  list/dict/set literal or constructor in the enclosing scope): the
  closure is not hashable state jit can key on, so mutation between
  calls changes numerics WITHOUT a recompile — the inverse failure,
  just as silent.
"""

from __future__ import annotations

import ast
from typing import Iterator

from fedml_tpu.analysis.core import (
    Finding, Project, _terminal_name as _terminal, register_rule,
)

_RULE = "recompile-hazard"
_LOOPY_ENTRIES = {"jit", "pjit", "ProgramSite"}
_MUTABLE_CTORS = {"list", "dict", "set", "bytearray", "defaultdict",
                  "OrderedDict", "deque"}


@register_rule(
    _RULE,
    "jit compile sites inside loop bodies (a recompile per iteration) "
    "and jit-compiled closures over mutable Python values (numerics "
    "change without a recompile)",
)
def check(project: Project) -> Iterator[Finding]:
    for relpath, mod in sorted(project.modules.items()):
        yield from _loops(mod)
        yield from _closures(mod)


def _loops(mod) -> Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.For, ast.While)):
            continue
        # manual stack walk PRUNING nested defs/lambdas: their bodies
        # execute when the stored callable is called, not per
        # iteration (ast.walk + `continue` would still descend)
        stack = list(ast.iter_child_nodes(node))
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(sub))
            # only IMMEDIATE invocation retraces per iteration —
            # `jax.jit(f)(x)` in a loop. Building jitted callables in a
            # setup loop (one per bucket, stored) compiles lazily once
            # per callable and is the elastic idiom, not the hazard.
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Call) \
                    and _terminal(sub.func.func) in _LOOPY_ENTRIES \
                    and sub.func.args:
                scope = mod.enclosing_function(sub.lineno)
                yield Finding(
                    rule=_RULE, path=mod.relpath, line=sub.lineno,
                    scope=scope,
                    message=(
                        f"`{_terminal(sub.func.func)}(...)(...)` "
                        f"invoked inside a loop body traces+compiles "
                        f"every iteration — hoist the compile site or "
                        f"use CompiledRoundCache"
                    ),
                )


def _closures(mod) -> Iterator[Finding]:
    for qual, fi in sorted(mod.functions.items()):
        node = fi.node
        if isinstance(node, ast.Lambda):
            continue
        # names bound to mutable containers in THIS function's body
        mutable: set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign):
                val = sub.value
                is_mut = isinstance(val, (ast.List, ast.Dict, ast.Set,
                                          ast.ListComp, ast.DictComp,
                                          ast.SetComp)) or (
                    isinstance(val, ast.Call)
                    and _terminal(val.func) in _MUTABLE_CTORS
                )
                if is_mut:
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            mutable.add(t.id)
        if not mutable:
            continue
        # nested callables handed to a compile site
        nested_defs = {n.name: n for n in ast.walk(node)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))
                       and n is not node}
        for sub in ast.walk(node):
            if not (isinstance(sub, ast.Call)
                    and _terminal(sub.func) in _LOOPY_ENTRIES | {
                        "shard_map", "CompiledRoundCache"}
                    and sub.args):
                continue
            fn_arg = sub.args[0]
            target = None
            if isinstance(fn_arg, ast.Lambda):
                target = fn_arg
            elif isinstance(fn_arg, ast.Name) \
                    and fn_arg.id in nested_defs:
                target = nested_defs[fn_arg.id]
            if target is None:
                continue
            bound = _bound_names(target)
            frees = {
                n.id for n in ast.walk(target)
                if isinstance(n, ast.Name)
                and isinstance(n.ctx, ast.Load)
                and n.id in mutable and n.id not in bound
            }
            for name in sorted(frees):
                scope = mod.enclosing_function(sub.lineno)
                yield Finding(
                    rule=_RULE, path=mod.relpath, line=sub.lineno,
                    scope=scope,
                    message=(
                        f"jit-compiled closure captures mutable "
                        f"`{name}` — mutation between calls changes "
                        f"numerics without a recompile; pass it as an "
                        f"operand or freeze it (tuple/frozen "
                        f"dataclass)"
                    ),
                )


def _bound_names(fn_node) -> set[str]:
    out: set[str] = set()
    if isinstance(fn_node, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
        a = fn_node.args
        for arg in (a.posonlyargs + a.args + a.kwonlyargs):
            out.add(arg.arg)
        if a.vararg:
            out.add(a.vararg.arg)
        if a.kwarg:
            out.add(a.kwarg.arg)
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
            out.add(sub.id)
    return out

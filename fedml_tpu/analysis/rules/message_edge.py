"""message-edge: every message-type constant is a complete edge.

A message type is three obligations, not one: a HANDLER registration
(an unhandled type is dropped silently by the manager's dispatch), a
``MSG_TYPE_NAMES`` entry (the per-type byte counters
``transport.bytes_by_type.<name>`` fall back to a bare integer —
PR 7's wire-reduction accounting becomes unreadable), and payload
access behind the receive-edge validation discipline every inbound
payload follows (compress.validate_payload / tier.validate_partial:
``msg.get(...)`` with explicit screening — never a raw
``msg.payload[...]`` subscript that KeyErrors the dispatch thread on
a malformed frame).

Constants are recognized by shape: module-level ``MSG_*`` names bound
to integer literals (``MSG_TYPE_C2S_RESULT = 3``,
``MSG_SNN_ACTS = 101``).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from fedml_tpu.analysis.core import (
    Finding, Project, _terminal_name, register_rule,
)

_RULE = "message-edge"
_CONST_RE = re.compile(r"^MSG_[A-Z0-9_]+$")


@register_rule(
    _RULE,
    "every MSG_* message-type constant needs a handler registration, "
    "a MSG_TYPE_NAMES entry, and validated payload access in handlers",
)
def check(project: Project) -> Iterator[Finding]:
    consts: dict[str, tuple[str, int]] = {}  # name -> (path, line)
    handled: set[str] = set()
    named: set[str] = set()
    handler_fns: list[tuple[str, str]] = []  # (modpath, fn simple name)

    for relpath, mod in sorted(project.modules.items()):
        for node in mod.tree.body:  # module level only
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and _CONST_RE.match(node.targets[0].id) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, int):
                consts[node.targets[0].id] = (mod.relpath, node.lineno)

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = node.func.attr \
                if isinstance(node.func, ast.Attribute) else (
                    node.func.id if isinstance(node.func, ast.Name)
                    else None)
            if fname == "register_message_receive_handler" and node.args:
                cname = _const_name(node.args[0])
                if cname:
                    handled.add(cname)
                if len(node.args) > 1:
                    h = node.args[1]
                    if isinstance(h, ast.Attribute):
                        handler_fns.append((mod.relpath, h.attr))
                    elif isinstance(h, ast.Name):
                        handler_fns.append((mod.relpath, h.id))
            elif (fname == "update" and node.args
                    and isinstance(node.func, ast.Attribute)
                    and _terminal_name(node.func.value)
                    == "MSG_TYPE_NAMES"
                    and isinstance(node.args[0], ast.Dict)):
                for k in node.args[0].keys:
                    cname = _const_name(k)
                    if cname:
                        named.add(cname)

        # MSG_TYPE_NAMES = { CONST: "name", ... } literal
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) \
                    and any(_terminal_name(t) == "MSG_TYPE_NAMES"
                            for t in node.targets) \
                    and isinstance(node.value, ast.Dict):
                for k in node.value.keys:
                    cname = _const_name(k)
                    if cname:
                        named.add(cname)

    for cname, (path, line) in sorted(consts.items()):
        if cname not in handled:
            yield Finding(
                rule=_RULE, path=path, line=line, scope="<module>",
                message=(
                    f"message type {cname} has no "
                    f"register_message_receive_handler site — inbound "
                    f"frames of this type are dropped silently"
                ),
            )
        if cname not in named:
            yield Finding(
                rule=_RULE, path=path, line=line, scope="<module>",
                message=(
                    f"message type {cname} has no MSG_TYPE_NAMES "
                    f"entry — transport.bytes_by_type falls back to a "
                    f"bare integer for it"
                ),
            )

    # raw payload subscripts inside registered handlers — matched by
    # simple name WITHIN the registering module only (a same-named
    # function elsewhere in the project is not this handler)
    seen_handlers: set[tuple[str, str]] = set()
    for modpath, fname in handler_fns:
        for qual, fi in project.functions.items():
            if fi.name != fname or fi.module.relpath != modpath:
                continue
            key = (fi.module.relpath, qual)
            if key in seen_handlers:
                continue
            seen_handlers.add(key)
            node = fi.node
            if isinstance(node, ast.Lambda):
                continue
            args = [a.arg for a in node.args.args]
            msg_params = {a for a in args if a not in ("self", "cls")}
            for sub in ast.walk(node):
                if isinstance(sub, ast.Subscript) \
                        and isinstance(sub.value, ast.Attribute) \
                        and sub.value.attr == "payload" \
                        and isinstance(sub.value.value, ast.Name) \
                        and sub.value.value.id in msg_params \
                        and isinstance(sub.ctx, ast.Load):
                    scope = qual.split(":", 1)[1]
                    yield Finding(
                        rule=_RULE, path=fi.module.relpath,
                        line=sub.lineno, scope=scope,
                        message=(
                            f"raw payload subscript in receive "
                            f"handler `{scope}` — a malformed frame "
                            f"KeyErrors the dispatch thread; use "
                            f".get() behind receive-edge validation"
                        ),
                    )


def _const_name(expr) -> str | None:
    name = _terminal_name(expr)
    if name is not None and _CONST_RE.match(name):
        return name
    return None

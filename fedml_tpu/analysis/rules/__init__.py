"""fedlint rules. Importing this package registers every rule with
:data:`fedml_tpu.analysis.core.RULES` (docs/STATIC_ANALYSIS.md has the
catalog: each rule names the historical bug class it would have
caught)."""

from fedml_tpu.analysis.rules import (  # noqa: F401
    config_contract,
    donation,
    jit_purity,
    lock_hygiene,
    message_edge,
    metric_vocab,
    recompile_hazard,
    traced_branch,
)

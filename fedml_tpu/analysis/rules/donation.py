"""donation-discipline: donated buffers must not be read after the
call — the STATIC twin of memscope's runtime donation audit (PR 12).

``jax.jit(f, donate_argnums=(0,))(state)`` invalidates ``state``'s
buffers the moment the call dispatches; reading ``state`` afterwards
either crashes ("buffer has been deleted") or — worse, on backends
where XLA declined the alias — silently reads a stale copy while the
program pays the 2x footprint its donation claimed to eliminate. The
runtime audit (``mem.donation_misses``) catches the second failure
after the first execution; this rule catches both at review time.

Two shapes are tracked per straight-line block:

- ``g = jax.jit(f, donate_argnums=(0,))`` ... ``g(x)`` — ``x`` read
  later in the block without an intervening rebind;
- ``self._fn = ProgramSite(f, donate_argnums=(0,))`` in one method,
  ``self._fn(x)`` in another method of the same class.
"""

from __future__ import annotations

import ast
from typing import Iterator

from fedml_tpu.analysis.core import (
    Finding, JIT_ENTRY_NAMES, Project, register_rule, _terminal_name,
)
from fedml_tpu.analysis.rules._common import fn_scope

_RULE = "donation-discipline"


def _donate_argnums(call: ast.Call) -> tuple[int, ...] | None:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            nums = []
            for sub in ast.walk(kw.value):
                if isinstance(sub, ast.Constant) \
                        and isinstance(sub.value, int):
                    nums.append(sub.value)
            return tuple(nums)
    return None


def _is_donating_jit(call) -> tuple[int, ...] | None:
    if isinstance(call, ast.Call) \
            and _terminal_name(call.func) in JIT_ENTRY_NAMES:
        return _donate_argnums(call)
    return None


@register_rule(
    _RULE,
    "an argument donated to a jit-compiled call is read again in the "
    "same scope after the call (static twin of mem.donation audit)",
)
def check(project: Project) -> Iterator[Finding]:
    for relpath, mod in sorted(project.modules.items()):
        # module-level donating callables:
        # `g = jax.jit(f, donate_argnums=(0,))` at module scope
        module_donors: dict[str, tuple[int, ...]] = {}
        for node in mod.tree.body:
            if isinstance(node, ast.Assign):
                donate = _is_donating_jit(node.value)
                if donate:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            module_donors[t.id] = donate
        # class-wide donating attributes:
        # ("Cls", "_fn") -> donated argnums
        attr_donors: dict[tuple[str, str], tuple[int, ...]] = {}
        for qual, fi in mod.functions.items():
            if fi.cls is None:
                continue
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Assign):
                    continue
                donate = _is_donating_jit(node.value)
                if not donate:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        attr_donors[(fi.cls, t.attr)] = donate

        for qual, fi in mod.functions.items():
            if isinstance(fi.node, ast.Lambda):
                continue
            yield from _check_function(mod, fi, attr_donors,
                                       module_donors)


def _check_function(mod, fi, attr_donors, module_donors
                    ) -> Iterator[Finding]:
    scope = fn_scope(fi)

    def blocks(node):
        for field in ("body", "orelse", "finalbody"):
            stmts = getattr(node, field, None)
            if isinstance(stmts, list) and stmts \
                    and isinstance(stmts[0], ast.stmt):
                yield stmts
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                yield from blocks(child)

    for body in blocks(fi.node):
        yield from _check_block(mod, fi, scope, body, attr_donors,
                                module_donors)


_SIMPLE_STMTS = (ast.Assign, ast.AnnAssign, ast.AugAssign, ast.Expr,
                 ast.Return)


def _check_block(mod, fi, scope, body, attr_donors, module_donors
                 ) -> Iterator[Finding]:
    # local donating callables bound in this block
    local_donors: dict[str, tuple[int, ...]] = {}
    # donated-away names -> line of the donating call
    dead: dict[str, int] = {}
    for stmt in body:
        # reads of dead names anywhere in this statement's subtree
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Load) \
                    and node.id in dead:
                yield Finding(
                    rule=_RULE, path=mod.relpath, line=node.lineno,
                    scope=scope,
                    message=(
                        f"`{node.id}` was donated to a "
                        f"donate_argnums-compiled call and is read "
                        f"afterwards — its buffers are deleted (or "
                        f"silently undonated: mem.donation_misses)"
                    ),
                )
                dead.pop(node.id, None)  # one finding per donation
        # rebinds resurrect the name (conservatively, anywhere in the
        # subtree: a rebind on one If branch must not leave the other
        # branch's read flagged — branches may be exclusive)
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Store):
                dead.pop(node.id, None)

        # donors are tracked from STRAIGHT-LINE statements only;
        # nested If/For/With bodies are analyzed as their own blocks
        # (a donate inside an early-return branch must not poison the
        # sibling branch)
        if not isinstance(stmt, _SIMPLE_STMTS):
            continue

        if isinstance(stmt, ast.Assign):
            donate = _is_donating_jit(stmt.value)
            if donate:
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        local_donors[t.id] = donate
                continue

        assigned = _assign_targets(stmt)
        value = stmt.value
        if value is None:
            continue
        for node in ast.walk(value):
            if not isinstance(node, ast.Call):
                continue
            donate = None
            f = node.func
            if isinstance(f, ast.Name) and (
                    f.id in local_donors or f.id in module_donors):
                donate = local_donors.get(f.id) \
                    or module_donors.get(f.id)
            elif isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id == "self" and fi.cls is not None:
                donate = attr_donors.get((fi.cls, f.attr))
            elif _is_donating_jit(f):
                donate = _is_donating_jit(f)  # jit(f, donate=..)(x)
            if not donate:
                continue
            for idx in donate:
                if idx < len(node.args) \
                        and isinstance(node.args[idx], ast.Name):
                    name = node.args[idx].id
                    if name not in assigned:  # x = g(x) is the idiom
                        dead[name] = node.lineno


def _assign_targets(stmt) -> set[str]:
    out: set[str] = set()
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    out.add(n.id)
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        for n in ast.walk(stmt.target):
            if isinstance(n, ast.Name):
                out.add(n.id)
    return out

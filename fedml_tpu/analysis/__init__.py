"""fedlint: project-invariant static analysis (docs/STATIC_ANALYSIS.md).

Twelve PRs of review hardening fixed the same defect classes over and
over — host impurity inside compiled rounds, donated buffers reused
after the call, blocking work under locks, metric names missing from
the OBSERVABILITY.md vocabulary, config validation deferred past parse
time. FedJAX gets its safety from a narrow functional API; this repo
chose a wide one, so the invariants are machine-checked instead:
AST-level rules (:mod:`fedml_tpu.analysis.rules`) over a small scope /
call-graph framework (:mod:`fedml_tpu.analysis.core`), ratcheted in CI
via a frozen baseline (``scripts/fedlint.py --baseline``).

The analyzer (:mod:`.core` + :mod:`.rules`) imports NOTHING from the
code it lints — it parses it — so linting cannot perturb what it
lints; stdlib ``ast`` only, no jax. One module here IS runtime-shared
by design: :mod:`.flags`, the flag-registration checker run.py /
bench.py / the deploy supervisor call at startup (the runtime twin of
the parse-time-validation rule). This ``__init__`` stays import-free
so that runtime path pulls in none of the analyzer.
"""

"""Shared CLI flag-registration checker — the RUNTIME twin of the
parse-time-validation lint rule (docs/STATIC_ANALYSIS.md).

One registration contract for every entrypoint (run.py, bench.py, the
deploy/supervisor argv builders) instead of bench.py's hand-rolled
``_assert_no_reserved_flags``:

- :data:`RESERVED_RUN_FLAGS` names the option strings owned by the run
  CLI's SLO/export plane. ``--slo`` means an SloSpec and
  ``--metrics_port`` means the OpenMetrics listener on EVERY
  entrypoint — a bench stage minting its own ``--slo`` would shadow
  those semantics, so registering a collision fails loudly at parser
  build, not at first confused use. (Duplicate option strings need no
  runtime check: argparse already raises at ``add_argument`` time —
  the STATIC side of this contract, including literal duplicates, is
  the fedlint parse-time-validation rule.)

``check_flag_registry(parser)`` is called by non-owning entrypoints
(bench.py); the owner (run.py) calls it with ``owner=True``, which
additionally asserts the reserved flags are actually registered — the
reservation must never outlive the plane it protects.
"""

from __future__ import annotations

#: option strings owned by the run CLI's live-observability plane
#: (fedml_tpu/experiments/run.py: the SLO engine + OpenMetrics
#: exporter). The supervisor also strips these from client argv —
#: clients would collide on one bind (run.py keeps --metrics_port on
#: rank 0 only).
RESERVED_RUN_FLAGS = ("--slo", "--metrics_port")


def registered_option_strings(parser) -> list[str]:
    """Every option string the parser knows, in registration order."""
    return [s for act in parser._actions for s in act.option_strings]


def check_flag_registry(parser, *, reserved=RESERVED_RUN_FLAGS,
                        owner: bool = False,
                        entrypoint: str = "this entrypoint") -> None:
    """Validate a built parser's registrations. Raises ``SystemExit``
    (a config error the operator must fix, not a crash to swallow) on
    a reserved flag registered by a non-owner, or on a reserved flag
    MISSING from the owner. (Duplicates cannot survive to this point —
    argparse raises at ``add_argument`` time.)"""
    taken = registered_option_strings(parser)
    clash = sorted(set(taken).intersection(reserved))
    if owner:
        missing = sorted(set(reserved) - set(taken))
        if missing:
            raise SystemExit(
                f"{entrypoint} owns reserved flag(s) {missing} but "
                f"does not register them — the reservation must not "
                f"outlive the plane it protects "
                f"(fedml_tpu/analysis/flags.py)"
            )
        return
    if clash:
        raise SystemExit(
            f"{entrypoint} registered reserved flag(s) {clash}: these "
            f"names belong to the run CLI's SLO/export plane "
            f"(fedml_tpu/experiments/run.py) — rename the flag "
            f"(fedml_tpu/analysis/flags.py)"
        )


#: flags that name ONE listener bind (or one deep-profiling session)
#: per world and therefore belong to rank 0 only — every client of a
#: supervised world inheriting ``--metrics_port`` would collide on the
#: same bind, and every client inheriting ``--profile_on_breach``
#: would arm its own jax.profiler against a per-rank SLO view when
#: the breach the operator cares about is the round the SERVER closes
#: (run.py strips them from client argv; the Supervisor re-checks at
#: spawn)
RANK0_ONLY_FLAGS = ("--metrics_port", "--profile_on_breach")


def check_rank_argv(argv, rank: int) -> None:
    """Spawn-time safety net for supervised worlds: a client rank's
    argv must not carry a rank-0-exclusive bind flag. run.py's
    ``--supervise`` path strips them when BUILDING the argv; this
    re-check catches hand-built :class:`RankSpec` lists taking the
    same shortcut without the strip."""
    if rank == 0:
        return
    # match both argv forms argparse accepts: `--flag value` and
    # `--flag=value`
    present = {str(tok).split("=", 1)[0] for tok in argv}
    clash = sorted(present & set(RANK0_ONLY_FLAGS))
    if clash:
        raise SystemExit(
            f"client rank {rank} argv carries rank-0-only flag(s) "
            f"{clash} — every client would collide on the same bind; "
            f"strip them from client argv "
            f"(fedml_tpu/analysis/flags.py RANK0_ONLY_FLAGS)"
        )

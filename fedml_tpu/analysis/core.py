"""fedlint framework: file loader, scope/call-graph builder, rule
registry, suppressions, baseline ratchet, JSON + human output.

The analyzer is deliberately self-contained (stdlib ``ast`` only — no
jax import, no runtime import of the code under analysis): rules see a
:class:`Project` of parsed modules plus two derived indexes,

- a **call graph** resolving three call shapes — bare names to
  same-module (or from-imported) functions, ``self.m(...)`` /
  ``cls.m(...)`` to methods of the enclosing class, and
  ``mod.f(...)`` through the module's import aliases — precise enough
  to follow real code, conservative enough to never crash on dynamic
  dispatch (unresolvable calls simply add no edge);
- the **jit-reachable set**: every function transitively callable from
  a compile site — a call to ``jax.jit`` / ``jit`` / ``pjit``,
  ``memscope.ProgramSite``, ``shard_map``, or
  ``elastic.CompiledRoundCache`` (first positional argument is the
  traced callable; lambdas count, and their bodies are walked in the
  enclosing module scope). Rules like jit-purity and traced-branch key
  off this set, so "is this function allowed to touch the host?" is
  answered by the graph, not by convention.

Findings are identified by a line-number-free fingerprint
``sha1(rule|path|scope|message)`` so the ``--baseline`` ratchet file
survives unrelated edits: pre-existing findings stay frozen, anything
new fails the run (docs/STATIC_ANALYSIS.md "Baseline policy").
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import hashlib
import json
import os
import re
from typing import Any, Callable, Iterable, Iterator

__all__ = [
    "AnalysisConfig", "Finding", "FunctionInfo", "ModuleInfo", "Project",
    "RULES", "Rule", "load_baseline", "register_rule", "run_analysis",
    "write_baseline",
]

#: names whose call mints a jit compile site; the first positional
#: argument is the traced callable (fedavg.py `ProgramSite(self._round,
#: ...)`, elastic.py `CompiledRoundCache(fn, ...)`, compat.shard_map)
JIT_ENTRY_NAMES = frozenset(
    {"jit", "pjit", "ProgramSite", "shard_map", "CompiledRoundCache"}
)

_SUPPRESS_RE = re.compile(
    r"#\s*fedlint:\s*disable=([A-Za-z0-9_,\- ]+)"
)
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*fedlint:\s*disable-file=([A-Za-z0-9_,\- ]+)"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation.

    ``scope`` is the enclosing function qualname (or ``<module>``) and
    feeds the fingerprint together with rule, path, and message — NOT
    the line number, so baselined findings survive unrelated edits that
    shift lines."""

    rule: str
    path: str  # repo-root-relative, '/'-separated
    line: int
    message: str
    scope: str = "<module>"

    @property
    def fingerprint(self) -> str:
        key = f"{self.rule}|{self.path}|{self.scope}|{self.message}"
        return hashlib.sha1(key.encode()).hexdigest()[:12]

    def to_dict(self) -> dict[str, Any]:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "scope": self.scope, "message": self.message,
            "fingerprint": self.fingerprint,
        }

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclasses.dataclass
class FunctionInfo:
    """One function/method definition plus its outgoing call edges."""

    qualname: str  # "pkg.mod:Class.method" | "pkg.mod:func"
    module: "ModuleInfo"
    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    cls: str | None = None
    #: resolved callee qualnames (filled by Project._link_calls)
    callees: set[str] = dataclasses.field(default_factory=set)

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1].rsplit(":", 1)[-1]


class ModuleInfo:
    """One parsed source file: AST, import aliases, suppressions,
    function defs keyed by qualname."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.modname = self.relpath[:-3].replace("/", ".") \
            if self.relpath.endswith(".py") else self.relpath
        # alias -> imported module ("np" -> "numpy"); from-imports map
        # the bound name to "module.attr" ("sleep" -> "time.sleep")
        self.import_aliases: dict[str, str] = {}
        self.from_imports: dict[str, str] = {}
        self.functions: dict[str, FunctionInfo] = {}
        self._suppressed_lines: dict[int, set[str]] = {}
        self._suppressed_file: set[str] = set()
        self._collect_imports()
        self._collect_suppressions()
        self._collect_functions()

    # -- construction --------------------------------------------------

    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.import_aliases[a.asname or a.name.split(".")[0]] \
                        = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.from_imports[a.asname or a.name] = \
                        f"{node.module}.{a.name}"

    def _collect_suppressions(self) -> None:
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                rules = {r.strip() for r in
                         m.group(1).split(",") if r.strip()}
                # drop trailing free-text reason words ("rule  reason")
                rules = {r.split()[0] for r in rules}
                self._suppressed_lines.setdefault(i, set()).update(rules)
            m = _SUPPRESS_FILE_RE.search(line)
            if m:
                self._suppressed_file.update(
                    r.strip().split()[0] for r in m.group(1).split(",")
                    if r.strip()
                )

    def _collect_functions(self) -> None:
        mod = self

        class V(ast.NodeVisitor):
            def __init__(self) -> None:
                self.stack: list[str] = []
                self.cls: list[str] = []

            def visit_ClassDef(self, node: ast.ClassDef) -> None:
                self.cls.append(node.name)
                self.stack.append(node.name)
                self.generic_visit(node)
                self.stack.pop()
                self.cls.pop()

            def _def(self, node) -> None:
                self.stack.append(node.name)
                qual = f"{mod.modname}:" + ".".join(self.stack)
                mod.functions[qual] = FunctionInfo(
                    qual, mod, node, cls=self.cls[-1] if self.cls else None
                )
                self.generic_visit(node)
                self.stack.pop()

            visit_FunctionDef = _def
            visit_AsyncFunctionDef = _def

        V().visit(self.tree)

    # -- queries -------------------------------------------------------

    def suppressed(self, rule: str, line: int) -> bool:
        """True when ``# fedlint: disable=<rule>`` covers this line —
        on the line itself, anywhere in the contiguous comment block
        directly above it (so the disable can carry a multi-line
        reason, which the policy requires), or file-wide via
        ``disable-file``."""
        if rule in self._suppressed_file:
            return True
        if rule in self._suppressed_lines.get(line, ()):
            return True
        i = line - 1
        while i >= 1 and self.lines[i - 1].lstrip().startswith("#"):
            if rule in self._suppressed_lines.get(i, ()):
                return True
            i -= 1
        return False

    def enclosing_function(self, line: int) -> str:
        """Qualname suffix of the innermost def containing ``line``
        (fingerprint scope)."""
        best, best_span = "<module>", None
        for qual, fi in self.functions.items():
            node = fi.node
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= line <= end:
                span = end - node.lineno
                if best_span is None or span < best_span:
                    best, best_span = qual.split(":", 1)[1], span
        return best


@dataclasses.dataclass
class AnalysisConfig:
    """Repo-level analyzer config (``fedlint.json``).

    ``exempt`` maps rule name -> list of relpath glob patterns the rule
    skips entirely (policy exemptions live HERE, visible in one file —
    e.g. bench.py is exempt from jit-purity because its measurement
    loops intentionally time host work; inline ``# fedlint: disable``
    comments are for single intentional sites, with a reason).
    """

    exempt: dict[str, list[str]] = dataclasses.field(default_factory=dict)
    #: vocabulary source for the metric-vocabulary rule
    vocabulary_doc: str = "docs/OBSERVABILITY.md"
    #: extra rule knobs, keyed by rule name
    options: dict[str, Any] = dataclasses.field(default_factory=dict)

    @staticmethod
    def load(path: str | None, root: str) -> "AnalysisConfig":
        if path is None:
            cand = os.path.join(root, "fedlint.json")
            path = cand if os.path.exists(cand) else None
        if path is None:
            return AnalysisConfig()
        with open(path) as f:
            raw = json.load(f)
        return AnalysisConfig(
            exempt={k: list(v) for k, v in raw.get("exempt", {}).items()},
            vocabulary_doc=raw.get("vocabulary_doc",
                                   "docs/OBSERVABILITY.md"),
            options=raw.get("options", {}),
        )

    def exempted(self, rule: str, relpath: str) -> bool:
        return any(fnmatch.fnmatch(relpath, pat)
                   for pat in self.exempt.get(rule, ()))


class Project:
    """Every parsed module under the target paths, plus the call graph
    and the jit-reachable set rules key off."""

    def __init__(self, root: str, config: AnalysisConfig):
        self.root = os.path.abspath(root)
        self.config = config
        self.modules: dict[str, ModuleInfo] = {}  # relpath -> module
        self.functions: dict[str, FunctionInfo] = {}
        #: functions handed directly to a compile site, with the jit
        #: call's static_argnames resolved to parameter names
        self.jit_roots: dict[str, set[str]] = {}
        self.jit_reachable: set[str] = set()

    # -- loading -------------------------------------------------------

    @staticmethod
    def load(paths: Iterable[str], root: str,
             config: AnalysisConfig | None = None) -> "Project":
        config = config or AnalysisConfig()
        proj = Project(root, config)
        for p in paths:
            ap = os.path.abspath(p)
            # a mistyped/renamed target must FAIL, not lint an empty
            # set: exiting 0 'clean' would silently disable the CI gate
            if not os.path.exists(ap):
                raise SystemExit(f"fedlint: no such target: {p}")
            if os.path.isfile(ap) and not ap.endswith(".py"):
                raise SystemExit(
                    f"fedlint: not a python file: {p}"
                )
            if os.path.isdir(ap):
                for dirpath, dirnames, filenames in os.walk(ap):
                    dirnames[:] = [d for d in dirnames
                                   if d != "__pycache__"
                                   and not d.startswith(".")]
                    for fn in sorted(filenames):
                        if fn.endswith(".py"):
                            proj._add_file(os.path.join(dirpath, fn))
            elif ap.endswith(".py"):
                proj._add_file(ap)
        proj._link()
        return proj

    def _add_file(self, path: str) -> None:
        relpath = os.path.relpath(path, self.root)
        if relpath in self.modules:
            return
        with open(path, encoding="utf-8") as f:
            source = f.read()
        try:
            mod = ModuleInfo(path, relpath, source)
        except SyntaxError as err:  # a broken file is its own finding
            raise SystemExit(f"fedlint: cannot parse {relpath}: {err}")
        self.modules[mod.relpath] = mod
        self.functions.update(mod.functions)

    # -- call graph ----------------------------------------------------

    def _link(self) -> None:
        # index: simple function name -> qualnames, per module and per
        # (module, class)
        by_module: dict[tuple[str, str], str] = {}
        by_class: dict[tuple[str, str, str], str] = {}
        for qual, fi in self.functions.items():
            modname, local = qual.split(":", 1)
            simple = local.rsplit(".", 1)[-1]
            by_module.setdefault((modname, simple), qual)
            if fi.cls is not None:
                by_class[(modname, fi.cls, simple)] = qual

        # factory-returned closures — the repo's build_* idiom:
        # `self.local_update = build_local_update(...)` binds a nested
        # def the round body later calls (or hands to vmap/scan).
        # returns_of[F] = nested defs F returns; the use-site edges are
        # added in _resolve_calls.
        self._returns_of = {
            qual: self._returned_nested(fi)
            for qual, fi in self.functions.items()
        }
        self._attr_results: dict[tuple[str, str, str], set[str]] = {}
        for qual, fi in self.functions.items():
            if fi.cls is None:
                continue
            mod = fi.module
            for node in ast.walk(fi.node):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                targets = self._factory_targets(node.value, fi,
                                                by_module, by_class)
                if not targets:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        self._attr_results.setdefault(
                            (mod.modname, fi.cls, t.attr), set()
                        ).update(targets)

        for qual, fi in self.functions.items():
            fi.callees = self._resolve_calls(fi, by_module, by_class)
        self._find_jit_roots(by_module, by_class)
        self._close_reachability()

    def _returned_nested(self, fi: FunctionInfo) -> set[str]:
        """Qualnames of nested defs ``fi`` returns (directly, or one
        wrapper-call deep: ``return jax.jit(inner)``)."""
        node = fi.node
        if isinstance(node, ast.Lambda):
            return set()
        nested = {n.name for n in ast.walk(node)
                  if isinstance(n, (ast.FunctionDef,
                                    ast.AsyncFunctionDef))
                  and n is not node}
        if not nested:
            return set()
        out: set[str] = set()
        for r in ast.walk(node):
            if isinstance(r, ast.Return) and r.value is not None:
                for sub in ast.walk(r.value):
                    if isinstance(sub, ast.Name) and sub.id in nested:
                        cand = f"{fi.qualname}.{sub.id}"
                        if cand in self.functions:
                            out.add(cand)
        return out

    def _factory_targets(self, call: ast.Call, fi, by_module, by_class
                         ) -> set[str]:
        """Nested defs the factory ``call`` returns, or empty."""
        f = call.func
        mod = fi.module
        target = None
        if isinstance(f, ast.Name):
            target = self._resolve_name(f.id, mod, by_module)
        elif isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name):
                if f.value.id in ("self", "cls") and fi.cls:
                    target = by_class.get((mod.modname, fi.cls, f.attr))
                else:
                    tm = mod.import_aliases.get(f.value.id)
                    if tm is not None:
                        target = self._module_function(tm, f.attr,
                                                       by_module)
        if target is None:
            return set()
        return self._returns_of.get(target, set())

    def _resolve_calls(self, fi: FunctionInfo, by_module, by_class
                       ) -> set[str]:
        mod = fi.module
        out: set[str] = set()
        # function-local bindings of factory results:
        # `lu = build_local_update(...)` -> calling/handing-off `lu`
        # reaches the nested def the factory returned
        local_results: dict[str, set[str]] = {}
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                targets = self._factory_targets(node.value, fi,
                                                by_module, by_class)
                if targets:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            local_results.setdefault(t.id, set()) \
                                .update(targets)
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name):
                target = self._resolve_name(f.id, mod, by_module)
                if target:
                    out.add(target)
                out.update(local_results.get(f.id, ()))
            elif isinstance(f, ast.Attribute):
                base = f.value
                if isinstance(base, ast.Name) and base.id in ("self",
                                                              "cls"):
                    if fi.cls is not None:
                        t = by_class.get((mod.modname, fi.cls, f.attr))
                        if t:
                            out.add(t)
                        out.update(self._attr_results.get(
                            (mod.modname, fi.cls, f.attr), ()))
                elif isinstance(base, ast.Name):
                    target_mod = mod.import_aliases.get(base.id)
                    if target_mod is not None:
                        t = self._module_function(target_mod, f.attr,
                                                  by_module)
                        if t:
                            out.add(t)
            # callables escaping into combinators (`jax.vmap(lu)`,
            # `jax.vmap(self.local_update)`, `lax.scan(step, ...)`)
            # count as calls of what they wrap
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    out.update(local_results.get(arg.id, ()))
                elif isinstance(arg, ast.Attribute) \
                        and isinstance(arg.value, ast.Name) \
                        and arg.value.id in ("self", "cls") \
                        and fi.cls is not None:
                    out.update(self._attr_results.get(
                        (mod.modname, fi.cls, arg.attr), ()))
        return out

    def _resolve_name(self, name: str, mod: ModuleInfo, by_module
                      ) -> str | None:
        t = by_module.get((mod.modname, name))
        if t:
            return t
        dotted = mod.from_imports.get(name)
        if dotted:
            target_mod, _, attr = dotted.rpartition(".")
            return self._module_function(target_mod, attr, by_module)
        return None

    def _module_function(self, target_mod: str, attr: str, by_module
                         ) -> str | None:
        # imported module names rarely match our relpath-derived
        # modnames exactly (package vs file path); match by suffix
        for (modname, simple), qual in by_module.items():
            if simple == attr and (
                modname == target_mod
                or modname.endswith("." + target_mod.rsplit(".", 1)[-1])
                or target_mod.endswith(modname.rsplit(".", 1)[-1])
            ):
                return qual
        return None

    # -- jit roots + reachability -------------------------------------

    def _find_jit_roots(self, by_module, by_class) -> None:
        for relpath, mod in self.modules.items():
            for node in ast.walk(mod.tree):
                # decorator form: @jax.jit / @partial(jax.jit, ...)
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        if self._is_jit_expr(dec):
                            qual = self._qual_for_node(mod, node)
                            if qual:
                                self._add_root(
                                    qual,
                                    self._static_names(
                                        dec,
                                        self.functions.get(qual)))
                if not isinstance(node, ast.Call):
                    continue
                name = _terminal_name(node.func)
                if name not in JIT_ENTRY_NAMES or not node.args:
                    continue
                fn_arg = node.args[0]
                target = self._callable_target(fn_arg, mod, by_module,
                                               by_class, node)
                if target:
                    self._add_root(target,
                                   self._static_names(node,
                                                      self.functions
                                                      .get(target)))

        # lambdas handed to jit: their body's resolved calls are roots
        # too (handled by _callable_target returning a synthetic entry)

    def _qual_for_node(self, mod: ModuleInfo, node) -> str | None:
        for qual, fi in mod.functions.items():
            if fi.node is node:
                return qual
        return None

    def _callable_target(self, fn_arg, mod, by_module, by_class,
                         call) -> str | None:
        if isinstance(fn_arg, ast.Name):
            return self._resolve_name(fn_arg.id, mod, by_module)
        if isinstance(fn_arg, ast.Attribute) \
                and isinstance(fn_arg.value, ast.Name) \
                and fn_arg.value.id in ("self", "cls"):
            # ProgramSite(self._round, ...) inside a method: resolve in
            # the enclosing class
            encl = mod.enclosing_function(call.lineno)
            cls = encl.split(".", 1)[0] if "." in encl else None
            if cls:
                return by_class.get((mod.modname, cls, fn_arg.attr))
        if isinstance(fn_arg, ast.Lambda):
            # mark every function the lambda body calls as a root
            for sub in ast.walk(fn_arg.body):
                if isinstance(sub, ast.Call):
                    n = sub.func
                    if isinstance(n, ast.Name):
                        t = self._resolve_name(n.id, mod, by_module)
                        if t:
                            self._add_root(t, set())
        return None

    def _add_root(self, qual: str, static_names: set[str]) -> None:
        self.jit_roots.setdefault(qual, set()).update(static_names)

    def _static_names(self, call_or_dec, fn_info) -> set[str]:
        """Parameter names a jit site marks static (static_argnames
        literals, plus static_argnums resolved against the callee's
        positional parameters when it is known)."""
        out: set[str] = set()
        call = call_or_dec if isinstance(call_or_dec, ast.Call) else None
        if call is None:
            return out
        node = getattr(fn_info, "node", None) if fn_info else None
        params: list[str] = []
        if node is not None and not isinstance(node, ast.Lambda):
            params = [a.arg for a in node.args.args]
            if params and params[0] in ("self", "cls"):
                params = params[1:]
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for sub in ast.walk(kw.value):
                    if isinstance(sub, ast.Constant) \
                            and isinstance(sub.value, str):
                        out.add(sub.value)
            elif kw.arg == "static_argnums" and params:
                for sub in ast.walk(kw.value):
                    if isinstance(sub, ast.Constant) \
                            and isinstance(sub.value, int) \
                            and 0 <= sub.value < len(params):
                        out.add(params[sub.value])
        return out

    def _is_jit_expr(self, expr) -> bool:
        if _terminal_name(expr) in JIT_ENTRY_NAMES:
            return True
        if isinstance(expr, ast.Call):  # @partial(jax.jit, ...)
            if _terminal_name(expr.func) == "partial" and expr.args:
                return _terminal_name(expr.args[0]) in JIT_ENTRY_NAMES
            return self._is_jit_expr(expr.func)
        return False

    def _close_reachability(self) -> None:
        seen = set(self.jit_roots)
        frontier = list(self.jit_roots)
        while frontier:
            qual = frontier.pop()
            fi = self.functions.get(qual)
            if fi is None:
                continue
            for callee in fi.callees:
                if callee not in seen:
                    seen.add(callee)
                    frontier.append(callee)
        self.jit_reachable = seen


def _terminal_name(expr) -> str | None:
    """`jax.jit` -> "jit", `M.ProgramSite` -> "ProgramSite",
    `jit` -> "jit"."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


# ---------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------

@dataclasses.dataclass
class Rule:
    name: str
    doc: str
    check: Callable[[Project], Iterator[Finding]]


RULES: dict[str, Rule] = {}


def register_rule(name: str, doc: str):
    """Decorator: ``@register_rule("jit-purity", "...")`` over a
    ``check(project) -> Iterator[Finding]`` generator."""
    def deco(fn):
        RULES[name] = Rule(name=name, doc=doc, check=fn)
        return fn
    return deco


def _ensure_rules_loaded() -> None:
    from fedml_tpu.analysis import rules  # noqa: F401  (registers all)


# ---------------------------------------------------------------------
# baseline ratchet
# ---------------------------------------------------------------------

def load_baseline(path: str) -> set[str]:
    """Fingerprints frozen by a previous ``--write-baseline`` run."""
    with open(path) as f:
        raw = json.load(f)
    return {e["fingerprint"] for e in raw.get("findings", [])}


def write_baseline(path: str, findings: list[Finding]) -> None:
    """Freeze the CURRENT findings. Entries carry the human fields next
    to the fingerprint so a baseline diff reviews like code."""
    payload = {
        "version": 1,
        "note": "frozen fedlint findings — new findings fail CI; see "
                "docs/STATIC_ANALYSIS.md for the ratchet policy",
        "findings": sorted(
            (f.to_dict() for f in findings),
            key=lambda d: (d["rule"], d["path"], d["scope"],
                           d["message"]),
        ),
    }
    for e in payload["findings"]:
        e.pop("line", None)  # lines drift; fingerprints do not
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")


# ---------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------

def run_analysis(paths: Iterable[str], root: str,
                 config: AnalysisConfig | None = None,
                 rules: Iterable[str] | None = None,
                 ) -> list[Finding]:
    """Parse ``paths``, run every registered rule, return findings with
    suppression comments and config exemptions already applied."""
    _ensure_rules_loaded()
    config = config or AnalysisConfig()
    project = Project.load(paths, root, config)
    selected = list(rules) if rules else sorted(RULES)
    unknown = [r for r in selected if r not in RULES]
    if unknown:
        raise SystemExit(f"fedlint: unknown rule(s): {unknown} "
                         f"(have: {sorted(RULES)})")
    findings: list[Finding] = []
    for rname in selected:
        for f in RULES[rname].check(project):
            if config.exempted(rname, f.path):
                continue
            mod = project.modules.get(f.path)
            if mod is not None and mod.suppressed(rname, f.line):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings

"""CI smoke: an async + tiered gRPC world with a chaos-delayed straggler.

Drives the asynchronous/hierarchical aggregation contract end to end
over real sockets (docs/FAULT_TOLERANCE.md "Async + tiered worlds"):
a ROOT aggregator (``--tier_spec root:2 --async_buffer_k 1``) serves
two LEAF aggregators, each terminating two gRPC clients in its own
leaf world — one client is a chaos-delayed straggler, so its whole
leaf's partials arrive LATE while the sibling leaf keeps advancing the
model version. The run must:

- complete every emission (the root's summary reports all rounds and
  a finite evaluation — the world converged);
- fold the straggler leaf's late partials instead of dropping them
  (``async.stale_folds > 0`` in the root's metrics — the
  staleness-weighted buffer at work);
- actually reduce near the wire (``tier.partial_sums > 0`` at the
  root: every aggregate the root folded was a leaf partial, never a
  raw client delta).

The straggler client itself may exit nonzero: its final in-flight
result legitimately races the world's FINISH teardown (the leaf's
socket is already gone) — that race is the price of not waiting for
stragglers, and the assertion set above is the contract that matters.

Usage::

    python scripts/async_smoke.py OUT_DIR
"""

from __future__ import annotations

import json
import math
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# emissions (model versions) the root must produce: enough that the
# fast leaf's open loop spans several of the delayed leaf's slow
# cycles — the straggler leaf must land >= 1 (stale) partial while the
# world keeps moving
ROUNDS = 40


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def main(out_dir: str) -> int:
    os.makedirs(out_dir, exist_ok=True)
    cfg = {
        "data": {"dataset": "fake_mnist", "num_clients": 4,
                 "batch_size": 32, "partition_method": "homo", "seed": 0},
        "model": {"name": "lr", "num_classes": 10,
                  "input_shape": [28, 28, 1]},
        "train": {"lr": 0.1, "epochs": 1},
        "fed": {"algorithm": "fedavg", "num_rounds": ROUNDS,
                "clients_per_round": 4, "eval_every": ROUNDS,
                "async_buffer_k": 1, "staleness_fn": "poly"},
        "seed": 0,
        "run_name": "async_smoke",
        "out_dir": out_dir,
    }
    cfg_path = os.path.join(out_dir, "cfg.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    # three distinct worlds, nine listeners: the root world
    # {0: root, 1..2: leaves} plus one leaf world per leaf
    # {0: leaf, 1..2: its clients}
    ports = _free_ports(9)
    root_ip = os.path.join(out_dir, "root_world.json")
    with open(root_ip, "w") as f:
        json.dump({str(r): ["127.0.0.1", ports[r]] for r in range(3)}, f)
    leaf_ips = {}
    for leaf in (1, 2):
        path = os.path.join(out_dir, f"leaf{leaf}_world.json")
        base = 3 * leaf
        with open(path, "w") as f:
            json.dump({str(r): ["127.0.0.1", ports[base + r]]
                       for r in range(3)}, f)
        leaf_ips[leaf] = path
    env = _env()
    tdir = os.path.join(out_dir, "telemetry")

    def spawn(argv):
        return subprocess.Popen(
            [sys.executable, "-m", "fedml_tpu.experiments.run",
             "--config", cfg_path, "--backend", "grpc",
             "--ready_timeout", "180", *argv],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )

    procs = {}
    for leaf in (1, 2):
        for r in (1, 2):
            extra = []
            if leaf == 2 and r == 2:
                # THE straggler: every message this client sends or
                # receives is chaos-delayed, so leaf 2's rounds close
                # late and its partials arrive at the root with a
                # version lag > 0
                extra = ["--fault_seed", "7", "--fault_delay", "1.0",
                         "--fault_delay_max", "0.6"]
            procs[f"client{leaf}.{r}"] = spawn(
                ["--role", "client", "--rank", str(r),
                 "--world_size", "3", "--ip_config", leaf_ips[leaf],
                 *extra])
        procs[f"leaf{leaf}"] = spawn(
            ["--role", "leaf", "--rank", str(leaf),
             "--tier_spec", "root:2", "--world_size", "3",
             "--ip_config", leaf_ips[leaf],
             "--uplink_ip_config", root_ip,
             "--telemetry_dir", os.path.join(tdir, f"leaf{leaf}")])
    server = spawn(["--role", "server", "--tier_spec", "root:2",
                    "--world_size", "3", "--ip_config", root_ip,
                    "--telemetry_dir", tdir])

    s_out = server.communicate(timeout=420)[0]
    outs = {}
    for name, p in procs.items():
        try:
            outs[name] = p.communicate(timeout=90)[0]
        except subprocess.TimeoutExpired:
            p.kill()
            outs[name] = p.communicate()[0]
    if server.returncode != 0:
        raise SystemExit(f"root failed rc={server.returncode}:\n{s_out}")
    summary = json.loads(s_out.strip().splitlines()[-1])

    assert summary["rounds"] == ROUNDS, summary
    assert summary["async_buffer_k"] == 1, summary
    assert summary["tier_spec"] == "root:2", summary
    # converged: the end-of-run evaluation ran and produced a finite
    # loss on the emitted model
    assert math.isfinite(summary.get("loss", float("nan"))), summary
    for leaf in (1, 2):
        p = procs[f"leaf{leaf}"]
        assert p.returncode == 0, (leaf, outs[f"leaf{leaf}"])
        leaf_summary = json.loads(
            outs[f"leaf{leaf}"].strip().splitlines()[-1]
        )
        assert leaf_summary["status"] == "finished", leaf_summary
        assert leaf_summary["partials"] > 0, leaf_summary
    # the straggler may lose its final-result-vs-FINISH race (see
    # module docstring); every OTHER client must exit clean
    for name in ("client1.1", "client1.2", "client2.1"):
        assert procs[name].returncode == 0, (name, outs[name])

    with open(os.path.join(tdir, "metrics_rank0.json")) as f:
        counters = json.load(f).get("counters", {})
    stale = counters.get("async.stale_folds", 0)
    partials = counters.get("tier.partial_sums", 0)
    assert stale > 0, counters      # late partials FOLDED, not dropped
    assert partials > 0, counters   # the root only ever saw partials
    assert counters.get("async.emits", 0) == ROUNDS, counters

    print(json.dumps({
        "async_smoke": "ok",
        "rounds": summary["rounds"],
        "stale_folds": stale,
        "partial_sums": partials,
        "loss": summary.get("loss"),
        "straggler_rc": procs["client2.2"].returncode,
    }))
    return 0


if __name__ == "__main__":
    if len(sys.argv) != 2:
        raise SystemExit("usage: async_smoke.py OUT_DIR")
    sys.exit(main(sys.argv[1]))

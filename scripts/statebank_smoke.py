"""Client-state bank smoke (ci.sh; docs/FAULT_TOLERANCE.md
"Client-state banks").

The composed world PR 14 could not run — compress + streamed defense +
bulk — end to end on CPU, plus the crash contract:

1. a compressed (int8), median-defended, block-streamed run CONVERGES
   on the mnist_lr family shape (test accuracy up >= 0.15 over 12
   rounds, loss strictly down);
2. the defended+compressed block program's argument AND temp bytes
   stay FLAT (<= 1.5x) from C=64 to C=256 at B=16 and FIXED
   population — the EF bank rides as an O(population) donated operand
   whose bytes never scale with the cohort;
3. a SIGKILLed run restores its banks BITWISE: a child process
   checkpoints every round (the ``{"server", "bank"}`` composite) and
   records each round's bank digest; the parent SIGKILLs it mid-run,
   relaunches, and the relaunch must resume from round > 0 with a
   bank digest equal to the recorded one, then finish every round
   with a finite, decreasing loss;
4. the donation audit reports ZERO misses on the composed program;
5. the ``bank.*`` vocabulary (rows / row_bytes / resident_mb gauges,
   gathers / scatters counters) serves over a real /metrics scrape.

Usage: python scripts/statebank_smoke.py <workdir>
       (the child mode is internal: ``... <workdir> child``)
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CHILD_ROUNDS = 6


def _cfg_mod():
    from fedml_tpu.config import (
        DataConfig, ExperimentConfig, FedConfig, ModelConfig,
        TrainConfig,
    )

    def cfg(cohort, block, rounds=1, population=None, epochs=1,
            **fed_kw):
        population = cohort if population is None else population
        fed_kw.setdefault("eval_every", 10**9)
        fed_kw.setdefault("compress", "int8")
        fed_kw.setdefault("robust_method", "median")
        return ExperimentConfig(
            data=DataConfig(dataset="fake_mnist",
                            num_clients=population, batch_size=32,
                            seed=0),
            model=ModelConfig(name="lr", num_classes=10,
                              input_shape=(28, 28, 1)),
            train=TrainConfig(lr=0.1, epochs=epochs,
                              cohort_fused=False),
            fed=FedConfig(num_rounds=rounds, clients_per_round=cohort,
                          client_block_size=block, **fed_kw),
            seed=0,
        )

    return cfg


def _build(conf):
    from fedml_tpu.algorithms.fedavg import FedAvgSim
    from fedml_tpu.data.loaders import load_dataset
    from fedml_tpu.models import create_model

    return FedAvgSim(create_model(conf.model), load_dataset(conf.data),
                     conf)


def _bank_digest(sim) -> str:
    import jax
    import numpy as np

    h = hashlib.sha256()
    banks = sim.bank_state()
    for name in sorted(banks):
        h.update(name.encode())
        for leaf in jax.tree.leaves(banks[name]):
            h.update(np.ascontiguousarray(
                np.asarray(jax.device_get(leaf))
            ).tobytes())
    return h.hexdigest()


def child(workdir: str) -> int:
    """One harness-shaped run leg: restore (if a checkpoint exists),
    then run + checkpoint every round, recording each round's bank
    digest so the relaunch can prove the restore was bitwise."""
    from fedml_tpu.experiments.harness import Experiment
    from fedml_tpu.utils.checkpoint import RoundCheckpointer

    cfg = _cfg_mod()(cohort=8, block=4, rounds=CHILD_ROUNDS,
                     population=16, epochs=2)
    sim = _build(cfg)
    state = sim.init()
    ckpt = RoundCheckpointer(os.path.join(workdir, "ckpt"), keep=2)
    state, start = Experiment._restore_state(ckpt, sim, state)
    marker = os.path.join(workdir, "progress.json")
    if start > 0:
        # the relaunch leg: the restored bank must be BITWISE the one
        # the dead process recorded at its last completed round
        with open(marker) as f:
            recorded = json.load(f)
        assert recorded["round"] == start - 1, (recorded, start)
        got = _bank_digest(sim)
        assert got == recorded["bank_sha"], (
            "bank restore not bitwise: "
            f"{got} != {recorded['bank_sha']}"
        )
        with open(os.path.join(workdir, "resumed.json"), "w") as f:
            json.dump({"resumed_from": start}, f)
    losses = []
    for r in range(start, CHILD_ROUNDS):
        state, m = sim.run_round(state)
        losses.append(float(m["train_loss"]))
        Experiment._save_state(ckpt, sim, r, state)
        tmp = marker + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"round": r, "bank_sha": _bank_digest(sim),
                       "loss": losses[-1]}, f)
        os.replace(tmp, marker)
        time.sleep(0.3)  # give the parent a window to SIGKILL
    ckpt.close()
    with open(os.path.join(workdir, "done.json"), "w") as f:
        json.dump({"losses": losses, "start": start}, f)
    return 0


def main() -> int:
    workdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/bank_smoke"
    if len(sys.argv) > 2 and sys.argv[2] == "child":
        return child(workdir)
    os.makedirs(workdir, exist_ok=True)

    import jax
    import numpy as np

    from fedml_tpu.core import memscope as M
    from fedml_tpu.core import telemetry

    tdir = os.path.join(workdir, "telemetry")
    telemetry.configure(telemetry_dir=tdir, rank=0, metrics_port=0)
    cfg = _cfg_mod()

    # -- 1. compress + defense + bulk converges --------------------------
    conv = cfg(16, block=4, rounds=12, population=32, epochs=2)
    sim = _build(conv)
    state = sim.init()
    acc0 = sim.evaluate_global(state)["acc"]
    first = last = None
    for _ in range(conv.fed.num_rounds):
        state, m = sim.run_round(state)
        last = float(m["train_loss"])
        first = last if first is None else first
    acc1 = sim.evaluate_global(state)["acc"]
    assert last < first, f"loss did not fall: {first} -> {last}"
    assert acc1 > acc0 + 0.15, f"no convergence: {acc0} -> {acc1}"
    assert sim._ef_bank is not None and sim._stream_defense == "median"

    # -- 2. flat bytes across the cohort sweep, banks riding -------------
    foot = {}
    for c in (64, 256):
        s = _build(cfg(c, block=16, population=256))
        st = s.init()
        st, _ = s.run_round(st)
        jax.block_until_ready(jax.tree.leaves(st))
        rec = M.program_record("sim_bulk", s._program_key())
        assert rec is not None, "bulk program accounting missing"
        foot[c] = rec
        del s, st
    for field in ("argument_bytes", "temp_bytes"):
        lo, hi = foot[64][field], foot[256][field]
        assert max(lo, hi) <= 1.5 * max(1, min(lo, hi)), (
            f"{field} not flat across C with banks riding: {lo} -> {hi}"
        )

    # -- 3. SIGKILL mid-run; relaunch restores the banks bitwise ---------
    kdir = os.path.join(workdir, "kill")
    os.makedirs(kdir, exist_ok=True)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    argv = [sys.executable, os.path.abspath(__file__), kdir, "child"]
    marker = os.path.join(kdir, "progress.json")
    p = subprocess.Popen(argv, env=env, cwd=REPO)
    deadline = time.time() + 300
    while time.time() < deadline:
        if os.path.exists(marker):
            try:
                with open(marker) as f:
                    if json.load(f)["round"] >= 1:
                        break
            except (json.JSONDecodeError, KeyError):
                pass
        if p.poll() is not None:
            raise AssertionError(
                f"child exited ({p.returncode}) before the kill window"
            )
        time.sleep(0.05)
    else:
        p.kill()
        raise AssertionError("child never reached round 1")
    os.kill(p.pid, signal.SIGKILL)  # the deterministic preemption
    p.wait()
    assert not os.path.exists(os.path.join(kdir, "done.json")), (
        "child finished before the SIGKILL — no crash was tested"
    )
    r2 = subprocess.run(argv, env=env, cwd=REPO, timeout=600)
    assert r2.returncode == 0, "relaunch leg failed"
    with open(os.path.join(kdir, "resumed.json")) as f:
        resumed = json.load(f)["resumed_from"]
    assert resumed > 0, "relaunch did not resume from the checkpoint"
    with open(os.path.join(kdir, "done.json")) as f:
        done = json.load(f)
    assert done["start"] == resumed
    assert all(np.isfinite(v) for v in done["losses"])

    # -- 4. donation audit: zero misses on the composed program ----------
    assert telemetry.METRICS.counter("mem.donation_audits") >= 1
    misses = telemetry.METRICS.counter("mem.donation_misses")
    assert misses == 0, f"donation misses with banks riding: {misses}"

    # -- 5. bank.* vocabulary live on /metrics ---------------------------
    with open(os.path.join(tdir, "export_rank0.json")) as f:
        port = json.load(f)["port"]
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=5
    ).read().decode()
    for name in ("bank_rows", "bank_row_bytes", "bank_resident_mb",
                 "bank_gathers", "bank_scatters",
                 "defense_sketch_bins", "defense_sketch_mb"):
        assert name in body, f"{name} missing from /metrics"

    telemetry.shutdown()
    print(
        "statebank smoke ok: compress+defense+bulk acc "
        f"{acc0:.3f} -> {acc1:.3f}, flat bytes across 4x cohort, "
        f"SIGKILL resume from round {resumed} with bitwise banks, "
        "0 donation misses, bank.* gauges live"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

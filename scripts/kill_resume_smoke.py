"""CI smoke: SIGKILL a deployed server mid-run, relaunch, resume.

Drives the crash-recovery contract end to end over real sockets
(docs/FAULT_TOLERANCE.md "Recovery"): a 2-rank gRPC deployment runs
with ``--checkpoint_every 1``; once the round-1 checkpoint lands the
server is SIGKILLed (the deterministic spot preemption), the world is
relaunched into the same run directory, and the relaunched server must
report ``resumed_from > 0`` and finish every configured round.

Usage::

    python scripts/kill_resume_smoke.py OUT_DIR
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ROUNDS = 6


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def main(out_dir: str) -> int:
    os.makedirs(out_dir, exist_ok=True)
    cfg = {
        "data": {"dataset": "fake_mnist", "num_clients": 1,
                 "batch_size": 32, "partition_method": "homo", "seed": 0},
        "model": {"name": "lr", "num_classes": 10,
                  "input_shape": [28, 28, 1]},
        "train": {"lr": 0.1, "epochs": 1},
        "fed": {"algorithm": "fedavg", "num_rounds": ROUNDS,
                "clients_per_round": 1, "eval_every": ROUNDS},
        "seed": 0,
        "run_name": "kill_resume",
        "out_dir": out_dir,
    }
    cfg_path = os.path.join(out_dir, "cfg.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    ports = _free_ports(2)
    ip_path = os.path.join(out_dir, "ip.json")
    with open(ip_path, "w") as f:
        json.dump({str(r): ["127.0.0.1", ports[r]] for r in range(2)}, f)
    base = [sys.executable, "-m", "fedml_tpu.experiments.run",
            "--config", cfg_path, "--backend", "grpc",
            "--world_size", "2", "--ip_config", ip_path,
            "--ready_timeout", "120", "--checkpoint_every", "1",
            "--heartbeat_interval", "0.5", "--heartbeat_timeout", "8"]
    env = _env()

    def spawn(role, rank=None):
        argv = [*base, "--role", role]
        if rank is not None:
            argv += ["--rank", str(rank)]
        return subprocess.Popen(argv, env=env, cwd=REPO,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)

    # -- phase 1: run until the round-1 checkpoint lands, then SIGKILL --
    client = spawn("client", 1)
    server = spawn("server")
    ckpt_dir = os.path.join(out_dir, "kill_resume", "ckpt")
    deadline = time.monotonic() + 240
    killed = False
    while time.monotonic() < deadline:
        if server.poll() is not None:
            out = server.communicate()[0]
            client.kill()
            raise SystemExit(
                f"server exited rc={server.returncode} before the "
                f"kill point:\n{out}"
            )
        steps = []
        if os.path.isdir(ckpt_dir):
            steps = [int(d) for d in os.listdir(ckpt_dir) if d.isdigit()]
        if steps and max(steps) >= 1:
            os.kill(server.pid, signal.SIGKILL)
            killed = True
            break
        time.sleep(0.05)
    if not killed:
        server.kill()
        client.kill()
        raise SystemExit("round-1 checkpoint never appeared")
    server.wait(timeout=30)
    # the orphaned client notices the dead server (or we stop waiting)
    try:
        client.wait(timeout=60)
    except subprocess.TimeoutExpired:
        client.kill()
        client.wait(timeout=10)
    killed_round = max(
        int(d) for d in os.listdir(ckpt_dir) if d.isdigit()
    )
    print(f"phase 1: server SIGKILLed with checkpoints through round "
          f"{killed_round}")

    # -- phase 2: relaunch the same world; the server must resume --
    client = spawn("client", 1)
    server = spawn("server")
    s_out = server.communicate(timeout=300)[0]
    try:
        client.communicate(timeout=60)
    except subprocess.TimeoutExpired:
        client.kill()
    if server.returncode != 0:
        raise SystemExit(
            f"relaunched server failed rc={server.returncode}:\n{s_out}"
        )
    summary = json.loads(s_out.strip().splitlines()[-1])
    assert summary["resumed_from"] > 0, summary
    assert summary["rounds"] == ROUNDS, summary
    print(f"kill-resume smoke ok: resumed_from={summary['resumed_from']}"
          f", rounds={summary['rounds']}, acc={summary.get('acc'):.3f}")
    return 0


if __name__ == "__main__":
    if len(sys.argv) != 2:
        raise SystemExit("usage: kill_resume_smoke.py OUT_DIR")
    sys.exit(main(sys.argv[1]))

"""Bulk-client engine smoke (ci.sh; docs/PERFORMANCE.md "Bulk-client
execution").

A CPU-only end-to-end pass over the block-streaming round
(fedml_tpu/core/bulk.py):

1. two bulk sims at C=64 and C=256 (B=16, FIXED population so the
   dataset argument bytes are constant) leave ``mem.program.sim_bulk``
   accounting whose argument AND temp bytes are FLAT across the 4x
   cohort sweep — the O(block) law, where the stacked round's O(C)
   footprint grows (contrast-pinned against ``sim_round`` at the same
   shapes);
2. a real bulk training run CONVERGES on the mnist_lr family shape
   (test accuracy up >= 0.2 from init over 12 rounds) and its
   trajectory matches the stacked round's within the stated
   reassociation band;
3. the donation audit reports zero misses on the block program;
4. ``/metrics`` serves the ``bulk.*`` vocabulary over real HTTP
   (bulk_block_size / bulk_blocks_per_round / bulk_padded_slots /
   bulk_rounds).

Usage: python scripts/bulk_smoke.py <workdir>
"""

from __future__ import annotations

import os
import sys
import urllib.request

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> int:
    workdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/bulk_smoke"
    os.makedirs(workdir, exist_ok=True)

    import jax
    import numpy as np

    from fedml_tpu.algorithms.fedavg import FedAvgSim
    from fedml_tpu.config import (
        DataConfig, ExperimentConfig, FedConfig, ModelConfig,
        TrainConfig,
    )
    from fedml_tpu.core import memscope as M
    from fedml_tpu.core import telemetry
    from fedml_tpu.data.loaders import load_dataset
    from fedml_tpu.models import create_model

    tdir = os.path.join(workdir, "telemetry")
    telemetry.configure(telemetry_dir=tdir, rank=0, metrics_port=0)

    def cfg(cohort, block, rounds=1, population=256, epochs=1):
        return ExperimentConfig(
            data=DataConfig(dataset="fake_mnist",
                            num_clients=population, batch_size=32,
                            seed=0),
            model=ModelConfig(name="lr", num_classes=10,
                              input_shape=(28, 28, 1)),
            train=TrainConfig(lr=0.1, epochs=epochs,
                              cohort_fused=False),
            fed=FedConfig(num_rounds=rounds, clients_per_round=cohort,
                          eval_every=10**9,
                          client_block_size=block),
            seed=0,
        )

    def build(conf):
        return FedAvgSim(create_model(conf.model),
                         load_dataset(conf.data), conf)

    # -- 1. flat program footprint across a 4x cohort sweep --------------
    foot = {}
    for c in (64, 256):
        sim = build(cfg(c, block=16))
        state = sim.init()
        state, _ = sim.run_round(state)
        jax.block_until_ready(jax.tree.leaves(state))
        rec = M.program_record("sim_bulk", sim._program_key())
        assert rec is not None, "bulk program accounting missing"
        foot[c] = rec
        del sim, state
    for field in ("argument_bytes", "temp_bytes"):
        lo, hi = foot[64][field], foot[256][field]
        assert max(lo, hi) <= 1.5 * max(1, min(lo, hi)), (
            f"{field} not flat across C: {lo} -> {hi}"
        )
    # contrast: the stacked round at the same shapes grows O(C)
    stacked = {}
    for c in (64, 256):
        sim = build(cfg(c, block=0))
        state = sim.init()
        state, _ = sim.run_round(state)
        stacked[c] = M.program_record("sim_round", sim._bucket)
        del sim, state
    bulk_growth = (
        foot[256]["temp_bytes"] + foot[256]["argument_bytes"]
        - foot[64]["temp_bytes"] - foot[64]["argument_bytes"]
    )
    stacked_growth = (
        stacked[256]["temp_bytes"] + stacked[256]["argument_bytes"]
        - stacked[64]["temp_bytes"] - stacked[64]["argument_bytes"]
    )
    assert stacked_growth > 4 * max(1, abs(bulk_growth)), (
        f"stacked O(C) growth {stacked_growth} should dwarf bulk's "
        f"{bulk_growth}"
    )

    # -- 2. real convergence on the mnist_lr shape + stacked parity ------
    conv = cfg(16, block=4, rounds=12, population=32, epochs=2)
    sim = build(conv)
    state = sim.init()
    acc0 = sim.evaluate_global(state)["acc"]
    for _ in range(conv.fed.num_rounds):
        state, m = sim.run_round(state)
    acc1 = sim.evaluate_global(state)["acc"]
    assert acc1 > acc0 + 0.2, f"no convergence: {acc0} -> {acc1}"
    ref = build(ExperimentConfig(
        data=conv.data, model=conv.model, train=conv.train,
        fed=FedConfig(num_rounds=12, clients_per_round=16,
                      eval_every=10**9), seed=0,
    ))
    rstate = ref.init()
    for _ in range(12):
        rstate, _ = ref.run_round(rstate)
    for a, b in zip(jax.tree.leaves(state.variables),
                    jax.tree.leaves(rstate.variables)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-6)

    # -- 3. donation audit: zero misses on the block program -------------
    assert telemetry.METRICS.counter("mem.donation_audits") >= 1
    misses = telemetry.METRICS.counter("mem.donation_misses")
    assert misses == 0, f"donation misses on the bulk program: {misses}"

    # -- 4. bulk.* vocabulary live on /metrics ---------------------------
    import json

    with open(os.path.join(tdir, "export_rank0.json")) as f:
        port = json.load(f)["port"]
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=5
    ).read().decode()
    for name in ("bulk_block_size", "bulk_blocks_per_round",
                 "bulk_padded_slots", "bulk_rounds"):
        assert name in body, f"{name} missing from /metrics"

    telemetry.shutdown()
    print(
        "bulk smoke ok: flat footprint "
        f"(bulk growth {bulk_growth}B vs stacked {stacked_growth}B), "
        f"acc {acc0:.3f} -> {acc1:.3f}, 0 donation misses, "
        "bulk.* gauges live"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

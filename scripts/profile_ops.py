"""Amortized per-op microbenches: scan 20 inner iterations per timed call
so the ~1.4 ms dispatch overhead of the tunnelled backend washes out.

Answers: does XLA dense-expand the grouped conv at s2d widths (cpg=64,
C=10)? What do BN and the dense/residual glue cost?
"""
from __future__ import annotations

import sys

import jax
import jax.numpy as jnp
from jax import lax

sys.path.insert(0, str(__import__("pathlib").Path(__file__).resolve().parent.parent))

from fedml_tpu.core.anatomy import fetch_corrected_time

INNER = 20


def timeit(fn, *args, n=15, warmup=2):
    # ONE timing path: the shared fetch-corrected loop from the
    # round-anatomy plane, amortized again over the INNER-step scan
    return fetch_corrected_time(fn, *args, n=n, warmup=warmup) / INNER


def conv_flops(B, H, W, k, ci, co):
    return 2 * B * H * W * k * k * ci * co


def bench_conv_grad(B, H, W, cpg, C, k=3, tag=""):
    """Amortized fwd+bwd of one grouped conv: scan INNER gradient steps."""
    ci = cpg * C
    x0 = jnp.ones((B, H, W, ci), jnp.bfloat16) * 0.01
    w0 = jnp.ones((k, k, cpg, ci), jnp.bfloat16) * 0.01

    def one(x, w):
        def loss(x, w):
            y = lax.conv_general_dilated(
                x, w, (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                feature_group_count=C,
            )
            return jnp.sum(y.astype(jnp.float32) ** 2)

        gx, gw = jax.grad(loss, argnums=(0, 1))(x, w)
        return x - 1e-6 * gx.astype(x.dtype), w - 1e-6 * gw.astype(w.dtype)

    @jax.jit
    def run(x, w):
        def body(c, _):
            return one(*c), None
        (x, w), _ = lax.scan(body, (x, w), None, length=INNER)
        return x, w

    t = timeit(run, x0, w0)
    fl = 3 * conv_flops(B, H, W, k, cpg, cpg) * C
    print(f"{tag:28s} t={t*1e3:7.3f} ms useful={fl/t/1e12:6.2f} TF/s "
          f"mfu={fl/t/197e12*100:5.1f}%")
    return t


def bench_fwd_only(B, H, W, cpg, C, k=3, tag=""):
    ci = cpg * C
    x0 = jnp.ones((B, H, W, ci), jnp.bfloat16) * 0.01
    w0 = jnp.ones((k, k, cpg, ci), jnp.bfloat16) * 0.001

    @jax.jit
    def run(x, w):
        def body(x, _):
            y = lax.conv_general_dilated(
                x, w, (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                feature_group_count=C,
            )
            return y, None
        x, _ = lax.scan(body, x, None, length=INNER)
        return x

    t = timeit(run, x0, w0)
    fl = conv_flops(B, H, W, k, cpg, cpg) * C
    print(f"{tag:28s} t={t*1e3:7.3f} ms useful={fl/t/1e12:6.2f} TF/s "
          f"mfu={fl/t/197e12*100:5.1f}% bytes~{2*B*H*W*ci*2/1e6:.1f}MB "
          f"bw={(2*B*H*W*ci*2 + k*k*cpg*ci*2)/t/1e9:.0f}GB/s")
    return t


def bench_bn(B, H, W, ch, tag=""):
    import flax.linen as nn

    bn = nn.BatchNorm(use_running_average=False, momentum=0.9)
    x0 = jnp.ones((B, H, W, ch), jnp.bfloat16) * 0.01
    v = bn.init(jax.random.key(0), x0)

    @jax.jit
    def run(x):
        def body(x, _):
            y, _ = bn.apply(v, x, mutable=["batch_stats"])
            return y.astype(x.dtype), None
        x, _ = lax.scan(body, x, None, length=INNER)
        return x

    t = timeit(run, x0)
    by = 2 * B * H * W * ch * 2
    print(f"{tag:28s} t={t*1e3:7.3f} ms bw={by/t/1e9:.0f}GB/s")
    return t


def main():
    print("== does group width change lowering? (fwd, amortized) ==")
    bench_fwd_only(32, 16, 16, 128, 5, tag="grouped 128x5")
    bench_fwd_only(32, 16, 16, 320, 2, tag="grouped 320x2")
    bench_fwd_only(32, 16, 16, 64, 5, tag="grouped 64x5 (320 tot)")
    bench_fwd_only(32, 16, 16, 256, 5, tag="grouped 256x5 (1280 tot)")
    print("== fwd+bwd (amortized) ==")
    bench_conv_grad(32, 16, 16, 128, 5, tag="grouped 128x5")
    bench_conv_grad(32, 16, 16, 320, 2, tag="grouped 320x2")
    bench_conv_grad(32, 16, 16, 64, 10, tag="s2d st1 grouped 64x10")
    bench_conv_grad(32, 16, 16, 640, 1, tag="dense 640")
    print("== BN train-mode (amortized) ==")
    bench_bn(32, 16, 16, 640, tag="BN 16x16x640")
    bench_bn(32, 32, 32, 160, tag="BN 32x32x160")


if __name__ == "__main__":
    main()

"""Diff two bench artifacts and flag metric regressions.

The driver's ``BENCH_r<k>.json`` artifacts wrap a bench run as
``{"n", "cmd", "rc", "tail", ...}`` where ``tail`` holds the run's
stdout — one JSON record per metric line. This tool loads two such
artifacts (or raw ``runs/bench_latest.jsonl`` files, or any file of
JSON-record lines), matches records by metric name, and reports every
metric whose value moved beyond a noise threshold — the regression
gate ROADMAP item 5 asks for, so a perf PR's win (or loss) is a
machine-checked diff, not a by-eye comparison of JSON blobs.

Rules:

- direction comes from the unit: ``rounds/sec`` / ``hit_rate`` /
  ``% test acc`` regress DOWN; ``seconds`` / ``ms/round`` regress UP;
- records marked ``fallback`` (CPU measurements — the marked records
  ``bench.py`` emits when the TPU backend is unavailable) are NEVER
  compared against unmarked (TPU) baselines: the pair is reported as
  skipped, which is exactly the honest outcome for a BENCH_r05-style
  round;
- the default threshold (8%) sits above the observed window-to-window
  spread of the rate lines (``window_rates`` in each record bracket
  the best-of-3 estimator at a few percent);
- exit code is 0 in the default ADVISORY mode (CI runs it for the
  report); ``--strict`` exits 1 when any regression is flagged.

Usage::

    python scripts/bench_diff.py BENCH_r04.json BENCH_r05.json
    python scripts/bench_diff.py old.jsonl new.jsonl --threshold 0.05 --strict
"""

from __future__ import annotations

import argparse
import json
import sys

#: units where larger is better; anything in _LOWER regresses upward.
#: Units in NEITHER table are compared as higher-is-better and the
#: entry is annotated ``unit_assumed`` so a wrong guess is visible.
_HIGHER = ("rounds/sec", "hit_rate", "% test acc", "accuracy", "acc",
           # async/tier stage (bench --async-bench): emit throughput
           # per fan-in and the headline fan-in scaling ratio
           "emits/sec", "ratio",
           # round-fusion stage (bench --fused-bench): the companion
           # fedavg_mfu_*_fused records — the MFU-recovery acceptance
           # surface is a tracked value, not a side-field
           "mfu")
#: "MB peak": the --mem-bench peak-HBM records (peak_round_hbm_mb_*) —
#: memory growth is a regression; the fallback-mark rule above already
#: keeps analytic CPU records from ever diffing against device peaks.
#: "rounds": the rounds-to-target convergence family (bench
#: --lora-bench rounds_to_match_*, future rounds_to_acc_*) — needing
#: more rounds is a regression.
#: "%": the --anatomy-bench percentage records — the tracked one is
#: critical_path_overhead_pct (attribution cost vs anatomy-off; the
#: < 2% acceptance bar), where growth is a regression.
_LOWER = ("seconds", "ms/round", "s", "ms", "MB/round", "MB peak",
          "rounds", "%")


def extract_records(text: str) -> dict[str, dict]:
    """Pull metric records out of arbitrary bench output text: every
    line that parses as a JSON object with a ``metric`` key counts;
    last record per metric wins (the artifacts are append-only)."""
    recs: dict[str, dict] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            recs[rec["metric"]] = rec
    return recs


def load_bench(path: str) -> dict[str, dict]:
    """Load one artifact: a driver ``BENCH_r*.json`` wrapper (records
    live in its ``tail`` string), or a file of JSON-record lines
    (``runs/bench_latest.jsonl``, raw bench stdout)."""
    with open(path) as f:
        text = f.read()
    try:
        data = json.loads(text)
    except json.JSONDecodeError:
        data = None
    if isinstance(data, dict) and "metric" not in data:
        # driver wrapper: records are JSON lines inside the tail (and
        # optionally a pre-parsed record under "parsed")
        recs = extract_records(str(data.get("tail", "")))
        parsed = data.get("parsed")
        if isinstance(parsed, dict) and "metric" in parsed:
            recs.setdefault(parsed["metric"], parsed)
        return recs
    if isinstance(data, dict):  # a single record
        return {data["metric"]: data}
    return extract_records(text)


def _direction(unit: str) -> tuple[int, bool]:
    """``(direction, known)``: +1 when larger is better, -1 when
    smaller is better; ``known=False`` for units in neither table
    (assumed higher-is-better, annotated by the caller)."""
    if unit in _LOWER:
        return -1, True
    return 1, unit in _HIGHER


def diff_records(
    old: dict[str, dict], new: dict[str, dict], threshold: float
) -> dict:
    """Compare metric-by-metric; returns ``{regressions, improvements,
    unchanged, skipped, only_old, only_new}`` where each entry names
    the metric and the relative change."""
    out = {"regressions": [], "improvements": [], "unchanged": [],
           "skipped": [], "only_old": [], "only_new": []}
    for name in sorted(set(old) | set(new)):
        o, n = old.get(name), new.get(name)
        if o is None:
            out["only_new"].append(name)
            continue
        if n is None:
            out["only_old"].append(name)
            continue
        o_fb, n_fb = bool(o.get("fallback")), bool(n.get("fallback"))
        if o_fb != n_fb:
            out["skipped"].append({
                "metric": name,
                "reason": "cpu-fallback record on one side only — "
                          "never compared against TPU numbers",
            })
            continue
        ov, nv = o.get("value"), n.get("value")
        if not isinstance(ov, (int, float)) or not isinstance(
                nv, (int, float)) or ov == 0:
            out["skipped"].append(
                {"metric": name, "reason": "non-numeric or zero value"}
            )
            continue
        rel = (nv - ov) / abs(ov)
        entry = {
            "metric": name,
            "old": ov,
            "new": nv,
            "rel_change": round(rel, 4),
            "unit": o.get("unit", ""),
        }
        if o_fb:
            entry["fallback"] = "cpu"  # cpu-vs-cpu: comparable, marked
        direction, known = _direction(o.get("unit", ""))
        if not known:
            entry["unit_assumed"] = "higher-is-better"
        score = rel * direction
        if score < -threshold:
            out["regressions"].append(entry)
        elif score > threshold:
            out["improvements"].append(entry)
        else:
            out["unchanged"].append(entry)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="diff two BENCH_*.json / bench JSONL artifacts and "
                    "flag metric regressions beyond a noise threshold"
    )
    ap.add_argument("old", help="baseline artifact")
    ap.add_argument("new", help="candidate artifact")
    ap.add_argument("--threshold", type=float, default=0.08,
                    help="relative change below which a move is noise "
                         "(default 0.08, above the bench's "
                         "window-to-window spread)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when regressions are flagged "
                         "(default: advisory — report and exit 0)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full diff as one JSON object")
    a = ap.parse_args(argv)

    old, new = load_bench(a.old), load_bench(a.new)
    if not old and not new:
        print("no metric records found in either artifact",
              file=sys.stderr)
        return 0 if not a.strict else 1
    d = diff_records(old, new, a.threshold)
    if a.json:
        print(json.dumps(
            {"old": a.old, "new": a.new, "threshold": a.threshold, **d},
            indent=2,
        ))
    else:
        for e in d["regressions"]:
            note = (" [unit direction assumed higher-is-better]"
                    if "unit_assumed" in e else "")
            print(f"REGRESSION {e['metric']}: {e['old']} -> {e['new']} "
                  f"({e['rel_change']:+.1%}, {e['unit']}){note}")
        for e in d["improvements"]:
            print(f"improved   {e['metric']}: {e['old']} -> {e['new']} "
                  f"({e['rel_change']:+.1%})")
        for e in d["skipped"]:
            print(f"skipped    {e['metric']}: {e['reason']}")
        print(
            f"bench_diff: {len(d['regressions'])} regressions, "
            f"{len(d['improvements'])} improvements, "
            f"{len(d['unchanged'])} within ±{a.threshold:.0%}, "
            f"{len(d['skipped'])} skipped, "
            f"{len(d['only_old'])}/{len(d['only_new'])} only in "
            "old/new"
        )
    if d["regressions"] and a.strict:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

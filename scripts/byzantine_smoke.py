"""CI smoke: an adversarial 4-rank world must converge under defense.

Drives the Byzantine-resilience contract end to end in one process
(docs/FAULT_TOLERANCE.md "Threat model"): a 1-server + 3-client
loopback world where rank 1 sign-flips its delta (10x boost), the
server aggregates with multi-Krum, and quarantine is armed. The run
must complete every round, the defended global model must stay on the
clean trajectory (final train accuracy), and the defense plane must
have visibly excluded results (``defense.excluded`` > 0).

Usage::

    python scripts/byzantine_smoke.py OUT_DIR
"""

from __future__ import annotations

import json
import os
import sys
import threading

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ROUNDS = 6
WORLD = 4  # 1 server + 3 clients
N_CLIENTS = 3


def main(out_dir: str) -> int:
    os.makedirs(out_dir, exist_ok=True)
    import numpy as np

    from fedml_tpu.config import (
        DataConfig, ExperimentConfig, FedConfig, ModelConfig,
        TrainConfig,
    )
    from fedml_tpu.core import telemetry
    from fedml_tpu.core.adversary import AdversaryPolicy
    from fedml_tpu.core.reputation import QuarantinePolicy
    from fedml_tpu.core.transport.loopback import LoopbackHub
    from fedml_tpu.algorithms.distributed_fedavg import (
        FedAvgClientActor, FedAvgServerActor,
    )
    from fedml_tpu.algorithms.base import build_evaluator, make_task
    from fedml_tpu.data.loaders import load_dataset
    from fedml_tpu.models import create_model

    telemetry.configure(telemetry_dir=out_dir, rank=0)
    cfg = ExperimentConfig(
        data=DataConfig(dataset="fake_mnist", num_clients=N_CLIENTS,
                        batch_size=32, seed=0),
        model=ModelConfig(name="lr", num_classes=10,
                          input_shape=(28, 28, 1)),
        train=TrainConfig(lr=0.1, epochs=1),
        fed=FedConfig(num_rounds=ROUNDS, clients_per_round=N_CLIENTS,
                      eval_every=ROUNDS, robust_method="multikrum",
                      robust_num_adversaries=1),
        adversary=AdversaryPolicy(mode="sign_flip", ranks=(1,),
                                  scale=10.0, seed=7),
        seed=0,
    )
    data = load_dataset(cfg.data)
    model = create_model(cfg.model)
    hub = LoopbackHub()
    server = FedAvgServerActor(
        WORLD, hub.create(0), model, cfg, num_clients=N_CLIENTS,
        quarantine=QuarantinePolicy(threshold=1.0, decay=0.5),
    )
    clients = [
        FedAvgClientActor(r, WORLD, hub.create(r), model, data, cfg)
        for r in range(1, WORLD)
    ]
    threads = [threading.Thread(target=c.run, daemon=True)
               for c in clients]
    for t in threads:
        t.start()
    server.transport.start()
    server.start_round()
    server.run()
    for c in clients:
        c.transport.stop()
    for t in threads:
        t.join(timeout=10)
    server.transport.stop()

    assert server.done.is_set(), (
        f"adversarial world never completed: {server.failure}"
    )
    counters = telemetry.METRICS.snapshot()["counters"]
    excluded = counters.get("defense.excluded", 0)
    assert excluded > 0, (
        f"multi-Krum excluded nothing under a sign-flip adversary: "
        f"{counters}"
    )
    corrupted = counters.get("adversary.corrupted_results", 0)
    assert corrupted >= ROUNDS, counters

    # convergence: the DEFENDED global model classifies the test split
    # like a clean run would (a poisoned mean collapses to ~chance)
    arrays = data.to_arrays(pad_multiple=cfg.data.batch_size)
    ev = build_evaluator(model, make_task(data.task))
    metrics = {k: float(v) for k, v in
               ev(server.variables, arrays.test_x, arrays.test_y).items()}
    assert np.isfinite(metrics["loss"]), metrics
    assert metrics["acc"] > 0.9, (
        f"defended model failed to converge: {metrics} "
        f"(undefended sign-flip drives this toward chance)"
    )
    telemetry.flush()
    print(json.dumps({
        "byzantine_smoke": "ok",
        "rounds": server.round_idx,
        "defense_excluded": excluded,
        "corrupted_results": corrupted,
        "quarantined": server.quarantined_ranks,
        **metrics,
    }))
    return 0


if __name__ == "__main__":
    if len(sys.argv) != 2:
        raise SystemExit("usage: byzantine_smoke.py OUT_DIR")
    sys.exit(main(sys.argv[1]))

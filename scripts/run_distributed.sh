#!/bin/sh
# mpirun-shaped localhost launcher: 1 server + N client OS processes.
#
# Reference analogs:
#   fedml_experiments/distributed/fedavg/run_fedavg_distributed_pytorch.sh
#     (mpirun -np $PROCESS_NUM python main_fedavg.py ...)
#   fedml_experiments/distributed/fedavg_cross_silo/run_server.sh,
#     run_client.sh (one role per shell invocation)
#
# Usage:
#   scripts/run_distributed.sh NCLIENTS BACKEND [run.py args...]
# e.g.
#   scripts/run_distributed.sh 2 grpc --algorithm fedavg \
#     --dataset fake_mnist --model lr --num_classes 10 \
#     --input_shape 28 28 1 --client_num_in_total 2 \
#     --client_num_per_round 2 --comm_round 3 --epochs 1 --batch_size 32
#
# BACKEND in {tcp, grpc, trpc, pubsub, pubsub_blob}. Socket backends get
# a generated localhost ip_config; pub/sub backends get a broker daemon
# launched for the run's duration (the reference assumes an external MQTT
# broker; ours is fedml_tpu.core.transport.broker).
#
# Per-rank logs + the server's summary JSON land in $OUT (default
# runs/distributed). Exit status is the server process's.
set -e
cd "$(dirname "$0")/.."

NCLIENTS=${1:?usage: run_distributed.sh NCLIENTS BACKEND [run.py args...]}
BACKEND=${2:?usage: run_distributed.sh NCLIENTS BACKEND [run.py args...]}
shift 2
WORLD=$((NCLIENTS + 1))
OUT=${OUT:-runs/distributed}
mkdir -p "$OUT"

# free localhost ports: WORLD for socket backends + 1 for the broker
PORTS=$(python - "$((WORLD + 1))" <<'EOF'
import socket, sys
socks = [socket.socket() for _ in range(int(sys.argv[1]))]
for s in socks:
    s.bind(("127.0.0.1", 0))
print(" ".join(str(s.getsockname()[1]) for s in socks))
for s in socks:
    s.close()
EOF
)

BROKER_PID=""
EXTRA=""
case "$BACKEND" in
  pubsub|pubsub_blob)
    BROKER_PORT=$(echo "$PORTS" | awk '{print $NF}')
    python -m fedml_tpu.core.transport.broker --port "$BROKER_PORT" \
      > "$OUT/broker.log" 2>&1 &
    BROKER_PID=$!
    EXTRA="--broker 127.0.0.1:$BROKER_PORT"
    if [ "$BACKEND" = "pubsub_blob" ]; then
      mkdir -p "$OUT/blobs"
      EXTRA="$EXTRA --blob_dir $OUT/blobs"
    fi
    ;;
  *)
    python - "$WORLD" $PORTS > "$OUT/ip_config.json" <<'EOF'
import json, sys
world = int(sys.argv[1])
ports = [int(p) for p in sys.argv[2:2 + world]]
print(json.dumps({str(r): ["127.0.0.1", ports[r]] for r in range(world)}))
EOF
    EXTRA="--ip_config $OUT/ip_config.json"
    ;;
esac

cleanup() {
  [ -n "$BROKER_PID" ] && kill "$BROKER_PID" 2>/dev/null || true
}
trap cleanup EXIT INT TERM

# clients in the background (launch order is irrelevant: the deploy
# readiness handshake retries until the world is up)
CLIENT_PIDS=""
r=1
while [ "$r" -le "$NCLIENTS" ]; do
  python -m fedml_tpu.experiments.run "$@" \
    --role client --rank "$r" --world_size "$WORLD" \
    --backend "$BACKEND" $EXTRA --out_dir "$OUT" \
    > "$OUT/client_$r.log" 2>&1 &
  CLIENT_PIDS="$CLIENT_PIDS $!"
  r=$((r + 1))
done

# server in the foreground; its stdout JSON is the run summary. No
# pipeline here: POSIX sh has no pipefail, and `... | tee` would report
# tee's status instead of the server's
STATUS=0
python -m fedml_tpu.experiments.run "$@" \
  --role server --world_size "$WORLD" \
  --backend "$BACKEND" $EXTRA --out_dir "$OUT" \
  > "$OUT/server_summary.json" || STATUS=$?
cat "$OUT/server_summary.json"
# wait only the CLIENT pids — a plain `wait` would also block on the
# broker daemon, which serves until killed by the EXIT trap
for pid in $CLIENT_PIDS; do
  wait "$pid" || STATUS=$?
done
exit $STATUS

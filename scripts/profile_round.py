"""Break the headline bench round into components on the real chip.

Times (fetch-corrected, amortized) for the s2d headline config:
- full compiled round
- cohort grad_fn alone (one step's fwd+bwd)
- one step_body equivalent (grad + optimizer + gather + gating)
- aggregation/server_update alone

Timing rides the anatomy plane's shared fetch-corrected loop
(``fedml_tpu.core.anatomy.fetch_corrected_time`` — ONE timing path for
every offline profiling script), the round program compiles through
:class:`~fedml_tpu.core.memscope.ProgramSite` so the compile is timed
and memory-accounted exactly like the production sims'
(``mem.program.profile_round.*``), and each measured component lands in
the round-anatomy ring as its own entry — pass ``--telemetry_dir`` to
keep the ``perf.phase.*`` observations and the metrics snapshot.

Usage: python scripts/profile_round.py [--model resnet56_s2d]
"""
from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet56_s2d")
    ap.add_argument("--telemetry_dir", default=None,
                    help="keep the anatomy/metrics artifacts (phase "
                         "observations, mem.program accounting) here")
    args = ap.parse_args()

    import sys
    sys.path.insert(0, str(__import__("pathlib").Path(__file__).resolve().parent.parent))
    from bench import build_sim

    from fedml_tpu.core import anatomy, telemetry
    from fedml_tpu.core.anatomy import ANATOMY, fetch_corrected_time
    from fedml_tpu.core.memscope import ProgramSite

    if args.telemetry_dir:
        telemetry.configure(telemetry_dir=args.telemetry_dir, rank=0)
    anatomy.configure(anatomy=True)

    def measure(label, phase, fn, *a, n=30):
        """One timing path + one anatomy entry per measured component:
        the amortized seconds land in the ring (path='profile') and the
        perf.phase.* histogram the label maps to."""
        ANATOMY.begin_round(len(ANATOMY.ring_snapshot()), path="profile")
        t = fetch_corrected_time(fn, *a, n=n)
        ANATOMY.phase(phase, t)
        ANATOMY.end_round(wall_s=t)
        return t

    sim, data = build_sim(model_name=args.model)
    state = sim.init()
    # ProgramSite: the compile is timed (mem.compile_s) and
    # memory-accounted (mem.program.profile_round.*) — the same
    # accounting path the sims' round programs use
    site = ProgramSite(sim._round, family="profile_round")
    t_round = measure("full_round", "local",
                      lambda s: site("round", s, sim.arrays)[0], state,
                      n=40)
    print(f"full round: {t_round*1e3:.2f} ms  ({1/t_round:.1f} r/s)")

    counts = np.asarray(sim.arrays.counts)
    print(f"counts: mean={counts.mean():.0f} max={counts.max()} "
          f"mean_steps={np.mean(np.ceil(counts/32)):.2f} "
          f"max_steps={np.ceil(counts.max()/32):.0f}")

    # --- cohort grad_fn alone ---
    from fedml_tpu.algorithms.base import (
        build_cohort_local_update, make_task, make_client_optimizer,
        _tree_to_dtype, _static_vars_to_dtype,
    )
    import optax
    model = sim.model
    C, B = 10, 32
    task = make_task("classification")
    cfg = sim.cfg.train
    compute_dtype = jnp.dtype(cfg.compute_dtype)

    variables = model.init(jax.random.key(0))
    stacked = jax.tree.map(
        lambda v: jnp.broadcast_to(v[None], (C,) + v.shape) + 0.0, variables
    )
    x_cb = jnp.zeros((C, B, 32, 32, 3), jnp.float32) + 0.1
    y_cb = jnp.zeros((C, B), jnp.int32)
    w_cb = jnp.ones((C, B), jnp.float32)

    def loss_fn(stacked_params, static_stacked, x_cb, y_cb, w_cb, rng):
        variables = {
            **_static_vars_to_dtype(static_stacked, compute_dtype),
            "params": _tree_to_dtype(stacked_params, compute_dtype),
        }
        logits, new_vars = model.apply_cohort_train(
            variables, _tree_to_dtype(x_cb, compute_dtype), rng
        )
        sums = jax.vmap(task.metric_sums)(
            logits.astype(jnp.float32), y_cb, w_cb
        )
        loss = jnp.sum(sums["loss_sum"] / jnp.maximum(sums["w_sum"], 1.0))
        return loss, (new_vars, sums)

    grad_fn = jax.jit(jax.value_and_grad(loss_fn, has_aux=True))
    sp = stacked["params"]
    ss = {k: v for k, v in stacked.items() if k != "params"}
    rng = jax.random.key(1)
    t_grad = measure(
        "cohort_grad", "local",
        lambda p: grad_fn(p, ss, x_cb, y_cb, w_cb, rng)[1], sp, n=40
    )
    print(f"cohort grad_fn: {t_grad*1e3:.2f} ms")

    # --- grad + optimizer + gating (one full step body, minus data gather) ---
    opt = make_client_optimizer(cfg)
    opt_state = jax.vmap(opt.init)(sp)

    @jax.jit
    def step(variables, opt_state):
        params = variables["params"]
        sv = {k: v for k, v in variables.items() if k != "params"}
        (_, (new_vars, sums)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params, sv, x_cb, y_cb, w_cb, rng)
        updates, new_opt = opt.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        valid = sums["w_sum"] > 0
        sel = lambda n_, o: jax.tree.map(
            lambda a, b: jnp.where(
                valid.reshape((C,) + (1,) * (a.ndim - 1)), a, b
            ), n_, o,
        )
        return sel({**new_vars, "params": new_params}, variables), sel(
            new_opt, opt_state
        )

    t_step = measure("step_body", "local",
                     lambda v: step(v, opt_state)[0], stacked, n=40)
    print(f"step body (no gather): {t_step*1e3:.2f} ms")

    # --- data gather ---
    x = jnp.asarray(sim.arrays.x)
    b_idx = jnp.zeros((C, B), jnp.int32)

    @jax.jit
    def gather(b_idx):
        return jnp.take(x, b_idx, axis=0)

    t_g = measure("data_gather", "h2d", gather, b_idx, n=40)
    print(f"data gather: {t_g*1e3:.3f} ms")

    # implied steps from the round
    print(f"implied: round={t_round*1e3:.1f}ms; if k steps of "
          f"{t_step*1e3:.2f}ms -> k={t_round/t_step:.1f}")
    if args.telemetry_dir:
        telemetry.flush_metrics()


if __name__ == "__main__":
    main()

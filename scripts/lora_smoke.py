"""PEFT/LoRA smoke (ci.sh; docs/PERFORMANCE.md "Parameter-efficient
federated fine-tuning").

A CPU-only end-to-end pass over the adapter subsystem
(fedml_tpu/peft/):

1. adapter-only FedAvg on the tiny transformer NWP shape actually
   LEARNS (train loss strictly down over the run);
2. the frozen base is bitwise the init values after every round — no
   optimizer state, no delta, no drift;
3. the per-round wire bytes of the adapter+head subtree with the
   codec stacked are <= 1/50 of the full-delta payload at the SAME
   shape (the delta-size law the bench tracks as
   ``lora_wire_reduction_x``);
4. the donation audit reports zero misses on the partitioned round
   program;
5. the ``peft.*`` vocabulary is live on a real ``/metrics`` scrape
   (peft_trainable_params / peft_frozen_params / peft_adapter_wire_mb
   / peft_wire_ratio).

Usage: python scripts/lora_smoke.py <workdir>
"""

from __future__ import annotations

import os
import sys
import urllib.request

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> int:
    workdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/lora_smoke"
    os.makedirs(workdir, exist_ok=True)

    import jax
    import numpy as np

    from fedml_tpu import peft as PF
    from fedml_tpu.algorithms.fedavg import FedAvgSim
    from fedml_tpu.config import (
        DataConfig, ExperimentConfig, FedConfig, ModelConfig,
        TrainConfig,
    )
    from fedml_tpu.core import telemetry
    from fedml_tpu.core.compress import CompressionSpec, wire_ratio
    from fedml_tpu.data.natural import synthetic_stackoverflow_nwp
    from fedml_tpu.models import create_model

    tdir = os.path.join(workdir, "telemetry")
    telemetry.configure(telemetry_dir=tdir, rank=0, metrics_port=0)

    vocab = 256
    data = synthetic_stackoverflow_nwp(
        num_clients=8, vocab_size=vocab, seed=0,
        sentences_low=8, sentences_high=24,
    )
    cfg = ExperimentConfig(
        data=DataConfig(dataset="stackoverflow_nwp", num_clients=8,
                        batch_size=8, seed=0),
        model=ModelConfig(
            name="transformer_lm", num_classes=vocab + 4,
            input_shape=(20,),
            extra=(("embed_dim", 32), ("max_len", 32),
                   ("num_heads", 2), ("num_layers", 1),
                   ("vocab_size", vocab + 4)),
        ),
        train=TrainConfig(lr=0.3, epochs=1),
        fed=FedConfig(num_rounds=10, clients_per_round=4,
                      eval_every=10**9, peft="lora", lora_rank=4,
                      lora_alpha=8.0,
                      lora_targets=("q_proj", "v_proj")),
        seed=0,
    )
    sim = FedAvgSim(create_model(cfg.model), data, cfg)
    state = sim.init()
    # snapshot the init values from a SEPARATE deterministic init():
    # device_get on the live state would create a zero-copy host view
    # on CPU — an external reference that blocks XLA from consuming
    # the donated buffers and turns the donation audit below into a
    # false miss (the same alias class as the PR 1 checkpoint bug)
    frozen0 = sim._peft.part.frozen(
        jax.device_get(sim.init().variables["params"])
    )

    # -- 1. the adapter run learns ---------------------------------------
    losses = []
    for _ in range(cfg.fed.num_rounds):
        state, m = sim.run_round(state)
        losses.append(float(jax.device_get(m["train_loss"])))
    assert losses[-1] < losses[0] - 0.05, (
        f"adapter-only training did not learn: {losses[0]:.4f} -> "
        f"{losses[-1]:.4f}"
    )

    # -- 2. frozen base bitwise-unchanged --------------------------------
    frozen_n = sim._peft.part.frozen(
        jax.device_get(state.variables["params"])
    )
    for a, b in zip(jax.tree.leaves(frozen0),
                    jax.tree.leaves(frozen_n)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), (
            "frozen base drifted"
        )

    # -- 3. the delta-size law at this shape -----------------------------
    params = jax.device_get(state.variables["params"])
    plan = sim._peft
    cspec = CompressionSpec(method="topk_int8", topk_frac=0.01)
    full_bytes = plan.full_wire_bytes(params)
    agg = plan.agg_part.trainable(params)
    lora_bytes = plan.adapter_wire_bytes(params) / wire_ratio(cspec,
                                                              agg)
    reduction = full_bytes / lora_bytes
    assert reduction >= 50.0, (
        f"per-round wire bytes only {reduction:.1f}x below the "
        "full-delta payload (bar: 50x)"
    )

    # -- 4. donation audit: zero misses on the partitioned round ---------
    assert telemetry.METRICS.counter("mem.donation_audits") >= 1
    misses = telemetry.METRICS.counter("mem.donation_misses")
    assert misses == 0, f"donation misses on the peft round: {misses}"

    # -- 5. peft.* vocabulary live on /metrics ---------------------------
    import json

    with open(os.path.join(tdir, "export_rank0.json")) as f:
        port = json.load(f)["port"]
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=5
    ).read().decode()
    for name in ("peft_trainable_params", "peft_frozen_params",
                 "peft_adapter_wire_mb", "peft_wire_ratio"):
        assert name in body, f"{name} missing from /metrics"

    telemetry.shutdown()
    print(
        f"lora smoke ok: loss {losses[0]:.3f} -> {losses[-1]:.3f}, "
        "frozen base bitwise, wire reduction "
        f"{reduction:.0f}x (>= 50x bar), 0 donation misses, "
        "peft.* gauges live"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

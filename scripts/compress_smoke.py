"""CI smoke: the compressed weight-update wire over real gRPC sockets.

Drives the wire-compression contract end to end
(docs/PERFORMANCE.md "Wire compression"): the SAME 1-server +
2-client gRPC world runs twice — dense, then under
``--compress topk_int8`` — and the per-message-type byte counters
(``transport.bytes_by_type.*``, docs/OBSERVABILITY.md) must show:

- the DELTA payloads (``c2s_result`` bytes observed by the server)
  shrank by at least 4x vs the dense run;
- the sync broadcast (``s2c_sync_model``) stayed dense — the claim is
  attributable to the compressed payload class, not to traffic mix;
- ``compress.decode_errors == 0`` (every payload validated and
  decompressed) and the compressed run converged (finite final loss,
  all rounds completed).

Usage::

    python scripts/compress_smoke.py OUT_DIR
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ROUNDS = 4


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _run_world(out_dir: str, tag: str, compress_args: list[str]):
    """One 3-rank gRPC world; returns (server summary, server rank-0
    metric counters)."""
    run_dir = os.path.join(out_dir, tag)
    os.makedirs(run_dir, exist_ok=True)
    cfg = {
        "data": {"dataset": "fake_mnist", "num_clients": 2,
                 "batch_size": 32, "partition_method": "homo",
                 "seed": 0},
        "model": {"name": "lr", "num_classes": 10,
                  "input_shape": [28, 28, 1]},
        "train": {"lr": 0.1, "epochs": 1},
        "fed": {"algorithm": "fedavg", "num_rounds": ROUNDS,
                "clients_per_round": 2, "eval_every": ROUNDS},
        "seed": 0,
        "run_name": tag,
        "out_dir": run_dir,
    }
    cfg_path = os.path.join(run_dir, "cfg.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    ports = _free_ports(3)
    ip_path = os.path.join(run_dir, "ip.json")
    with open(ip_path, "w") as f:
        json.dump({str(r): ["127.0.0.1", ports[r]] for r in range(3)},
                  f)
    telemetry_dir = os.path.join(run_dir, "telemetry")
    base = [sys.executable, "-m", "fedml_tpu.experiments.run",
            "--config", cfg_path, "--backend", "grpc",
            "--world_size", "3", "--ip_config", ip_path,
            "--ready_timeout", "120",
            "--telemetry_dir", telemetry_dir, *compress_args]
    env = _env()

    def spawn(role, rank=None):
        argv = [*base, "--role", role]
        if rank is not None:
            argv += ["--rank", str(rank)]
        return subprocess.Popen(argv, env=env, cwd=REPO,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)

    clients = [spawn("client", r) for r in (1, 2)]
    server = spawn("server")
    s_out = server.communicate(timeout=420)[0]
    for p in clients:
        try:
            p.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            p.kill()
            p.communicate()
    if server.returncode != 0:
        raise SystemExit(
            f"[{tag}] server failed rc={server.returncode}:\n{s_out}"
        )
    summary = json.loads(s_out.strip().splitlines()[-1])
    with open(os.path.join(telemetry_dir, "metrics_rank0.json")) as f:
        counters = json.load(f).get("counters", {})
    return summary, counters


def main(out_dir: str) -> int:
    os.makedirs(out_dir, exist_ok=True)
    dense_summary, dense = _run_world(out_dir, "dense", [])
    comp_summary, comp = _run_world(
        out_dir, "compressed",
        ["--compress", "topk_int8", "--compress_topk_frac", "0.05"],
    )

    assert dense_summary["rounds"] == ROUNDS, dense_summary
    assert comp_summary["rounds"] == ROUNDS, comp_summary
    assert comp_summary["compress"] == "topk_int8", comp_summary
    # the run converged: the final global model evaluates finite
    import math

    assert math.isfinite(comp_summary["loss"]), comp_summary

    d_result = dense["transport.bytes_by_type.c2s_result"]
    c_result = comp["transport.bytes_by_type.c2s_result"]
    reduction = d_result / c_result
    assert reduction >= 4.0, (
        f"delta-payload reduction {reduction:.2f}x < 4x "
        f"(dense {d_result}B vs compressed {c_result}B)"
    )
    # attribution: the sync broadcast stayed dense (byte-identical)
    assert (comp["transport.bytes_by_type.s2c_sync_model"]
            == dense["transport.bytes_by_type.s2c_sync_model"]), (
        comp, dense,
    )
    assert comp.get("compress.decode_errors", 0) == 0, comp

    print(json.dumps({
        "compress_smoke": "ok",
        "rounds": comp_summary["rounds"],
        "delta_payload_reduction": round(reduction, 2),
        "c2s_result_bytes": {"dense": d_result,
                             "topk_int8": c_result},
        "decode_errors": comp.get("compress.decode_errors", 0),
        "loss": comp_summary.get("loss"),
        "acc": comp_summary.get("acc"),
    }))
    return 0


if __name__ == "__main__":
    if len(sys.argv) != 2:
        raise SystemExit("usage: compress_smoke.py OUT_DIR")
    sys.exit(main(sys.argv[1]))

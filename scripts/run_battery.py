"""One-command experiment battery: the reference's 7-algorithm
comparison (``/root/reference/Makefile:5-13`` ->
``scripts/experiments/run_fed_experiment.sh``: each algorithm x N
seeded repetitions on MNIST, hetero alpha=0.1, r=0.1 -> 6000 samples,
10 clients all participating, 5 local epochs, 50 rounds) driven through
the harness repetition runner.

Usage::

    python scripts/run_battery.py                 # full battery
    python scripts/run_battery.py --reps 5        # reference rep count
    python scripts/run_battery.py --algorithms fedavg fedgdkd --rounds 10

Writes ``<out>/battery.jsonl`` (one summary record per repetition) and
prints a grouped mean +- std table — the equivalent of the reference's
wandb-grouped comparison report.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Honor JAX_PLATFORMS even on hosts whose sitecustomize pins the platform
# via jax.config (same escape hatch as experiments/run.py) — e.g.
# JAX_PLATFORMS=cpu runs the battery without the TPU tunnel.
if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

BATTERY_ALGORITHMS = (
    # the Makefile's run-example-experiments list, in its order
    "baseline", "centralized", "fedavg", "fedmd", "fd_faug", "feddtg",
    "fedgdkd",
)


def battery_config(algorithm: str, rounds: int, epochs: int, out_dir: str):
    from fedml_tpu.config import (
        DataConfig, ExperimentConfig, FedConfig, ModelConfig, TrainConfig,
    )

    return ExperimentConfig(
        data=DataConfig(
            dataset="fake_mnist", num_clients=10,
            partition_method="hetero", partition_alpha=0.1,
            batch_size=32, seed=0,
        ),
        model=ModelConfig(
            # the battery's homogeneous client config
            # (experiment_client_configs/homogeneous_all_participating
            # .json: cnn_medium everywhere)
            name="cnn_medium", num_classes=10, input_shape=(28, 28, 1),
        ),
        # reference battery client-optimizer defaults
        # (standalone/utils/config.py:31-37: sgd, lr 0.01, wd 0.001)
        train=TrainConfig(lr=0.01, weight_decay=1e-3, epochs=epochs),
        fed=FedConfig(
            algorithm=algorithm, num_rounds=rounds,
            clients_per_round=10, eval_every=10,
        ),
        seed=0,
        run_name=algorithm,
        out_dir=out_dir,
    )


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--algorithms", nargs="+",
                    default=list(BATTERY_ALGORITHMS))
    ap.add_argument("--reps", type=int, default=1,
                    help="seeded repetitions per algorithm "
                    "(reference battery: 5)")
    ap.add_argument("--rounds", type=int, default=50)
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--out", type=str, default="runs/battery")
    args = ap.parse_args()

    from fedml_tpu.experiments.harness import ALGORITHMS, Experiment

    unknown = [a for a in args.algorithms if a not in ALGORITHMS]
    if unknown:
        raise SystemExit(
            f"unknown algorithms {unknown}; known: {sorted(ALGORITHMS)}"
        )

    os.makedirs(args.out, exist_ok=True)
    jsonl_path = os.path.join(args.out, "battery.jsonl")
    rows = []
    t_start = time.perf_counter()
    with open(jsonl_path, "w") as jf:
        for algo in args.algorithms:
            cfg = battery_config(algo, args.rounds, args.epochs, args.out)
            t0 = time.perf_counter()
            try:
                summaries = Experiment(cfg, repetitions=args.reps).run()
            except Exception as err:  # one algorithm must not sink
                print(f"[battery] {algo} FAILED: {err}", flush=True)
                jf.write(json.dumps(
                    {"algorithm": algo, "failed": str(err)}
                ) + "\n")
                jf.flush()
                rows.append((algo, 0, float("nan"), float("nan"),
                             time.perf_counter() - t0))
                continue
            wall = time.perf_counter() - t0
            for rep, s in enumerate(summaries):
                rec = {
                    "algorithm": algo, "rep": rep,
                    **{k: v for k, v in s.items()
                       if isinstance(v, (int, float, str))},
                }
                jf.write(json.dumps(rec) + "\n")
                jf.flush()
            accs = [s.get("test_acc") for s in summaries
                    if s.get("test_acc") is not None]
            mean = sum(accs) / len(accs) if accs else float("nan")
            std = (
                (sum((a - mean) ** 2 for a in accs) / len(accs)) ** 0.5
                if accs else float("nan")
            )
            # reps with a test_acc in their summary (some sims emit
            # other final metrics, e.g. online DSGD's regret)
            rows.append((algo, len(accs), mean, std, wall))
            print(
                f"[battery] {algo}: test_acc {mean:.4f} +- {std:.4f} "
                f"({len(accs)}/{len(summaries)} reps with test_acc, "
                f"{wall:.0f}s)", flush=True,
            )

    print(f"\nBattery summary ({args.reps} reps x {args.rounds} rounds, "
          f"{time.perf_counter() - t_start:.0f}s total) -> {jsonl_path}")
    print(f"{'algorithm':<14} {'reps':>4} {'test_acc':>9} {'std':>8} "
          f"{'wall_s':>7}")
    for algo, n, mean, std, wall in rows:
        print(f"{algo:<14} {n:>4} {mean:>9.4f} {std:>8.4f} {wall:>7.0f}")


if __name__ == "__main__":
    main()

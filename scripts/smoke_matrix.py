"""CI smoke matrix in ONE process.

Runs the same CLI invocations ci.sh used to launch as separate
``python -m fedml_tpu.experiments.run`` processes, but through
``run.main(argv)`` in-process: the argv surface and the harness are
exercised identically while the jax/backend startup (~8-10 s per process
on the tunnelled host) and in-process compile caches are paid once.

Usage: python scripts/smoke_matrix.py <out_dir>
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import sys
import time

sys.path.insert(
    0, str(__import__("pathlib").Path(__file__).resolve().parent.parent)
)

from fedml_tpu.experiments import run as cli


def invoke(tag: str, argv: list[str], out_dir: str) -> None:
    t0 = time.perf_counter()
    print(f"  -- {tag}", flush=True)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli.main(argv)
    assert rc == 0, (tag, rc)
    out = buf.getvalue()
    # every smoke must emit a summary line carrying a real metric
    line = out.strip().splitlines()[0]
    rec = json.loads(line)
    assert any(
        k in rec
        for k in ("train_loss", "train_acc", "test_acc", "regret",
                  "final_regret", "test_auc")
    ), (tag, line)
    with open(os.path.join(out_dir, f"smoke_{tag}.json"), "w") as f:
        f.write(out)
    print(f"     ok ({time.perf_counter() - t0:.1f}s)", flush=True)


def fedavg_args(dataset, model, num_classes, input_shape, out_dir, tag):
    return [
        "--algorithm", "fedavg", "--dataset", dataset, "--model", model,
        "--client_num_in_total", "4", "--client_num_per_round", "2",
        "--comm_round", "2", "--epochs", "1", "--batch_size", "16",
        "--lr", "0.03", "--frequency_of_the_test", "2",
        "--num_classes", str(num_classes),
        "--input_shape", *input_shape.split(),
        "--out_dir", out_dir, "--run_name", f"smoke_{tag}",
    ]


def main() -> int:
    out_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/fedml_smoke"
    os.makedirs(out_dir, exist_ok=True)
    for ds, model, nc, shape in [
        ("synthetic", "lr", 10, "60"),
        ("fake_mnist", "lr", 10, "28 28 1"),
        ("fake_mnist", "cnn", 10, "28 28 1"),
        ("fake_cifar10", "resnet20", 10, "32 32 3"),
        ("fake_shakespeare", "rnn", 90, "80"),
        ("fake_stackoverflow_lr", "tag_lr", 50, "1000"),
    ]:
        tag = f"fedavg_{ds}_{model}"
        invoke(tag, fedavg_args(ds, model, nc, shape, out_dir, tag),
               out_dir)

    invoke("robust", [
        "--algorithm", "fedavg_robust", "--dataset", "fake_mnist",
        "--model", "lr", "--client_num_in_total", "4",
        "--client_num_per_round", "4", "--comm_round", "2",
        "--epochs", "1", "--batch_size", "16", "--num_classes", "10",
        "--input_shape", "28", "28", "1", "--robust_method", "median",
        "--robust_norm_clip", "1.0", "--robust_noise_stddev", "0.001",
        "--out_dir", out_dir, "--run_name", "smoke_robust",
    ], out_dir)
    invoke("vfl", [
        "--algorithm", "vfl", "--dataset", "fake_vfl",
        "--comm_round", "4", "--lr", "0.1", "--batch_size", "32",
        "--frequency_of_the_test", "4",
        "--out_dir", out_dir, "--run_name", "smoke_vfl",
    ], out_dir)
    invoke("turboaggregate", [
        "--algorithm", "turboaggregate", "--dataset", "fake_mnist",
        "--model", "lr", "--client_num_in_total", "8",
        "--client_num_per_round", "4", "--comm_round", "2",
        "--num_classes", "10", "--input_shape", "28", "28", "1",
        "--frequency_of_the_test", "2",
        "--out_dir", out_dir, "--run_name", "smoke_ta",
    ], out_dir)
    invoke("dol_dsgd", [
        "--algorithm", "dol_dsgd", "--dataset", "fake_susy",
        "--client_num_in_total", "4", "--comm_round", "50",
        "--lr", "0.3", "--out_dir", out_dir, "--run_name", "smoke_dol",
    ], out_dir)
    return 0


if __name__ == "__main__":
    sys.exit(main())

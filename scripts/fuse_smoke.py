"""Round-fusion CPU smoke (ci.sh): a tiny sim at ``--fuse_rounds 4``
must (a) reproduce the unfused run's final loss, (b) compile ONE block
program per (bucket, K) — churn-free blocks after the first are
compile-cache hits, (c) log a stacked metrics row for EVERY round (a
fused block must never swallow its non-boundary rounds' records), and
(d) keep eval on the exact boundary rounds even though
``eval_every % K != 0`` (docs/PERFORMANCE.md "Round fusion").

Run: ``JAX_PLATFORMS=cpu python scripts/fuse_smoke.py``
"""

from __future__ import annotations

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def main() -> int:
    import jax
    import numpy as np

    from fedml_tpu.config import (
        DataConfig,
        ExperimentConfig,
        FedConfig,
        ModelConfig,
        TrainConfig,
    )
    from fedml_tpu.core import telemetry
    from fedml_tpu.algorithms.fedavg import FedAvgSim
    from fedml_tpu.data.loaders import load_dataset
    from fedml_tpu.models import create_model

    ROUNDS, FUSE = 8, 4

    def cfg(fuse: int) -> ExperimentConfig:
        return ExperimentConfig(
            data=DataConfig(dataset="fake_mnist", num_clients=8,
                            batch_size=32, seed=0),
            model=ModelConfig(name="lr", num_classes=10,
                              input_shape=(28, 28, 1)),
            train=TrainConfig(lr=0.1, epochs=1),
            # eval_every=3 does NOT divide K=4: blocks must shorten to
            # flush exactly on rounds 2, 5, 7
            fed=FedConfig(num_rounds=ROUNDS, clients_per_round=4,
                          eval_every=3, fuse_rounds=fuse,
                          elastic_buckets=True),
            seed=0,
        )

    class Sink:
        def __init__(self):
            self.rows = []

        def log(self, row):
            self.rows.append(row)

    telemetry.METRICS.enabled = True

    c_unfused = cfg(1)
    data = load_dataset(c_unfused.data)
    model = create_model(c_unfused.model)
    s_unf = Sink()
    FedAvgSim(model, data, c_unfused).run(metrics_sink=s_unf)

    before = telemetry.METRICS.snapshot()["counters"]
    s_fused = Sink()
    FedAvgSim(model, data, cfg(FUSE)).run(metrics_sink=s_fused)
    after = telemetry.METRICS.snapshot()["counters"]

    # (c) one stacked-metrics row per round, evals on the boundary
    rounds = [r["round"] for r in s_fused.rows]
    assert rounds == list(range(ROUNDS)), rounds
    evals = [r["round"] for r in s_fused.rows if "test_acc" in r]
    assert evals == [2, 5, 7], evals

    # (a) parity with the unfused run (scan reassociation band only)
    unf = {r["round"]: r for r in s_unf.rows}
    for row in s_fused.rows:
        np.testing.assert_allclose(
            row["train_loss"], unf[row["round"]]["train_loss"],
            rtol=1e-5, atol=1e-6,
        )
    final_f = s_fused.rows[-1]
    final_u = unf[ROUNDS - 1]
    np.testing.assert_allclose(final_f["test_loss"],
                               final_u["test_loss"],
                               rtol=1e-5, atol=1e-6)

    # (b) one compile per (bucket, K): the eval cadence cuts the 8
    # rounds into blocks of lengths (3, 3, 2) over ONE bucket ->
    # exactly 2 distinct block programs compile and the repeated
    # length-3 block is a cache hit
    misses = after.get("elastic.compile_cache_misses", 0) - before.get(
        "elastic.compile_cache_misses", 0
    )
    hits = after.get("elastic.compile_cache_hits", 0) - before.get(
        "elastic.compile_cache_hits", 0
    )
    assert misses == 2, (misses, hits)
    assert hits == 1, (misses, hits)

    print(
        f"fuse smoke ok: {ROUNDS} rounds at K={FUSE}, final loss "
        f"{final_f['test_loss']:.4f} == unfused {final_u['test_loss']:.4f}"
        f", {misses} block compiles / {hits} cache hits, evals at "
        f"{evals}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""fedlint CLI: project-invariant static analysis with a CI ratchet.

Usage:
    python scripts/fedlint.py fedml_tpu/ [bench.py scripts/ ...]
        [--baseline fedlint_baseline.json] [--write-baseline]
        [--json out.json] [--rules jit-purity,lock-hygiene]
        [--config fedlint.json] [--root .] [--list-rules]

Exit codes: 0 = clean (or every finding baselined / suppressed),
1 = NEW findings (the ratchet: pre-existing findings are frozen in the
baseline file; anything new fails), 2 = usage error.

docs/STATIC_ANALYSIS.md has the rule catalog and the suppression /
baseline policy.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from fedml_tpu.analysis import core  # noqa: E402


def _discover_root(paths: list[str]) -> str:
    """The documented --root default: walk up from the first target
    looking for a ``fedlint.json``; its directory anchors relpaths (so
    baseline fingerprints match the committed ones regardless of CWD)
    and supplies the repo config. Falls back to CWD."""
    start = os.path.abspath(paths[0]) if paths else os.getcwd()
    cur = start if os.path.isdir(start) else os.path.dirname(start)
    while True:
        if os.path.exists(os.path.join(cur, "fedlint.json")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return os.getcwd()
        cur = parent


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="fedlint: AST-level project-invariant checks "
        "(docs/STATIC_ANALYSIS.md)")
    ap.add_argument("paths", nargs="*",
                    help="files / directories to analyze")
    ap.add_argument("--root", default=None,
                    help="repo root paths + baseline fingerprints are "
                    "relative to (default: the nearest directory at or "
                    "above the first target that holds a fedlint.json, "
                    "else CWD — so invocations from outside the repo "
                    "still load the repo config and produce "
                    "baseline-stable paths)")
    ap.add_argument("--config", default=None,
                    help="fedlint.json (default: <root>/fedlint.json "
                    "when present)")
    ap.add_argument("--baseline", default=None,
                    help="ratchet file: findings fingerprinted here "
                    "pass; new ones fail")
    ap.add_argument("--write-baseline", action="store_true",
                    help="freeze the CURRENT findings into --baseline "
                    "and exit 0")
    ap.add_argument("--json", dest="json_out", default=None,
                    help="write the full finding list as JSON "
                    "('-' = stdout)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset of rules to run")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="summary line only")
    args = ap.parse_args(argv)

    core._ensure_rules_loaded()
    if args.list_rules:
        for name in sorted(core.RULES):
            print(f"{name:24s} {core.RULES[name].doc}")
        return 0
    if not args.paths:
        ap.error("paths are required (except with --list-rules)")

    root = os.path.abspath(args.root) if args.root \
        else _discover_root(args.paths)
    try:
        config = core.AnalysisConfig.load(args.config, root)
        rules = [r.strip() for r in args.rules.split(",")] \
            if args.rules else None
        findings = core.run_analysis(args.paths, root, config, rules)
    except SystemExit as err:
        # core raises SystemExit(message) for usage-class errors
        # (unknown rule, unparseable target, broken config) — exit 2
        # per the documented contract, never 1 ('new findings')
        if isinstance(err.code, str):
            print(err.code, file=sys.stderr)
            return 2
        raise
    except (OSError, json.JSONDecodeError) as err:
        # unreadable --config / malformed json: same usage class
        print(f"fedlint: {err}", file=sys.stderr)
        return 2

    def emit_json(new, old):
        payload = {
            "new": [f.to_dict() for f in new],
            "baselined": [f.to_dict() for f in old],
            "rules": sorted(rules or core.RULES),
            "paths": args.paths,
        }
        text = json.dumps(payload, indent=2)
        if args.json_out == "-":
            print(text)
        else:
            with open(args.json_out, "w") as f:
                f.write(text + "\n")

    if args.write_baseline:
        if not args.baseline:
            print("fedlint: --write-baseline requires --baseline",
                  file=sys.stderr)
            return 2
        core.write_baseline(args.baseline, findings)
        if args.json_out:  # everything just frozen = baselined
            emit_json([], findings)
        print(f"fedlint: froze {len(findings)} finding(s) into "
              f"{args.baseline}",
              file=sys.stderr if args.json_out == "-" else sys.stdout)
        return 0

    baseline: set[str] = set()
    if args.baseline and os.path.exists(args.baseline):
        try:
            baseline = core.load_baseline(args.baseline)
        except (OSError, json.JSONDecodeError, KeyError,
                TypeError) as err:
            print(f"fedlint: corrupt baseline {args.baseline}: {err}",
                  file=sys.stderr)
            return 2
    new = [f for f in findings if f.fingerprint not in baseline]
    old = [f for f in findings if f.fingerprint in baseline]

    if args.json_out:
        emit_json(new, old)

    # with --json - the JSON document owns stdout; human output moves
    # to stderr so `fedlint --json - | jq` stays parseable
    human = sys.stderr if args.json_out == "-" else sys.stdout
    if not args.quiet:
        for f in new:
            print(f.render(), file=human)
    print(f"fedlint: {len(new)} new finding(s), {len(old)} baselined, "
          f"{len(findings)} total "
          f"({'FAIL' if new else 'ok'})", file=human)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())

"""Per-op time ledger for the STANDARD-layout ResNet-56 round.

VERDICT r4 weak #1: the reference-parity line (18.99 r/s, MFU 0.052)
explains its gap to peak qualitatively ("grouped-conv dense expansion")
but never itemizes it. This script produces the ledger:

- every distinct conv shape the cohort-grouped standard ResNet-56
  executes (stem, 3 stages x 9 blocks x 2 convs, stride-2 entries,
  1x1 projections), microbenched fwd+bwd in bf16 with inner-scan
  amortization (the only measurement style valid on the tunnelled
  backend — and ONLY on an idle chip, see docs/PERFORMANCE.md round-4
  negative result);
- each op's XLA-executed FLOPs (cost_analysis) vs its USEFUL FLOPs
  (the grouped math the semantics require) -> dense-expansion factor;
- composition: sum(op time x per-round count) vs the measured compiled
  round -> residual (BN/glue/latency);
- two bounds: the EXECUTED-op bound (the round cannot run faster than
  its constituent convs at this lowering) and the USEFUL-FLOP ideal
  (what de-expansion would buy at MXU peak).

Writes docs/ledger_resnet56_std.md (markdown table + bounds) and prints
the same. Run on an IDLE TPU: python scripts/ledger_resnet56_std.py
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

INNER = 20  # amortize the ~1.4 ms tunnel dispatch over an inner scan


def conv_shapes(cpg=(16, 32, 64), blocks=9, group=2, batch=32, hw=32):
    """Distinct conv invocations of one fwd pass of cohort-grouped
    standard ResNet-56 (reference model/cv/resnet.py:113 layout:
    conv3x3 stem, 3 stages x 9 basic blocks, channels 16/32/64,
    stride-2 at stage entries, 1x1 projection shortcuts), with
    per-round occurrence counts. Channels are x``group`` (clients
    concatenated), feature_group_count=``group``."""
    shapes = []  # (label, B, H, Cin, Cout, k, stride, fgc, count)
    shapes.append(("stem 3->16", batch, hw, 3 * group, cpg[0] * group,
                   3, 1, group, 1))
    h = hw
    for s, c in enumerate(cpg):
        C = c * group
        if s == 0:
            shapes.append((f"stage{s} 3x3 {c}->{c}", batch, h, C, C,
                           3, 1, group, 2 * blocks))
        else:
            prev = cpg[s - 1] * group
            shapes.append((f"stage{s} entry 3x3 {cpg[s-1]}->{c} /2",
                           batch, h, prev, C, 3, 2, group, 1))
            shapes.append((f"stage{s} proj 1x1 {cpg[s-1]}->{c} /2",
                           batch, h, prev, C, 1, 2, group, 1))
            h //= 2
            shapes.append((f"stage{s} 3x3 {c}->{c}", batch, h, C, C,
                           3, 1, group, 2 * blocks - 1))
    return shapes


def timed(fn, *args, n=10):
    """Best-of-3 amortized seconds per single op call."""
    out = fn(*args)  # compile+warm
    leaf = jax.tree.leaves(out)[0]
    float(np.asarray(jax.device_get(jnp.sum(leaf))))
    fetches = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(np.asarray(jax.device_get(jnp.sum(leaf))))
        fetches.append(time.perf_counter() - t0)
    fetch = min(fetches)
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn(*args)
        leaf = jax.tree.leaves(out)[0]
        float(np.asarray(jax.device_get(jnp.sum(leaf))))
        dt = time.perf_counter() - t0
        wall = max(dt - fetch, dt / 2)  # fetch-corrected, capped at 2x
        best = wall if best is None else min(best, wall)
    return best / n / INNER


def bench_conv(B, H, Cin, Cout, k, stride, fgc):
    """fwd+bwd time and executed FLOPs of ONE grouped conv in bf16."""
    x = jnp.zeros((B, H, H, Cin), jnp.bfloat16)
    w = jnp.zeros((k, k, Cin // fgc, Cout), jnp.bfloat16)
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NHWC", "HWIO", "NHWC"))
    pad = "SAME" if stride == 1 else [(k // 2, k // 2)] * 2

    def one(x, w):
        return lax.conv_general_dilated(
            x, w, (stride, stride), pad, dimension_numbers=dn,
            feature_group_count=fgc,
        )

    def fwd_bwd(x, w):
        def body(carry, _):
            xx, ww = carry
            loss, (dx, dw) = jax.value_and_grad(
                lambda a, b: jnp.sum(one(a, b).astype(jnp.float32)),
                argnums=(0, 1),
            )(xx, ww)
            return (xx + dx.astype(xx.dtype) * 0,
                    ww + dw.astype(ww.dtype) * 0), loss

        (xo, _), losses = lax.scan(body, (x, w), None, length=INNER)
        return xo, losses

    f = jax.jit(fwd_bwd)
    # executed FLOPs from the SINGLE-op grad program (HLO cost analysis
    # counts a scan body once, so costing the scan version would be
    # ambiguous across XLA versions)
    single = jax.jit(jax.grad(
        lambda a, b: jnp.sum(one(a, b).astype(jnp.float32)),
        argnums=(0, 1),
    ))
    try:
        ca = single.lower(x, w).compile().cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        executed = float(ca.get("flops") or 0) or None
    except Exception:
        executed = None
    t = timed(f, x, w)
    # useful fwd+bwd FLOPs: 3x the forward conv MACs x2 (fwd, dgrad,
    # wgrad), grouped semantics (Cin/fgc per output channel)
    Ho = H // stride
    useful = 3 * 2.0 * B * Ho * Ho * k * k * (Cin // fgc) * Cout
    return t, executed, useful


def main():
    dev = jax.devices()[0]
    print(f"device: {dev.device_kind} ({dev.platform})", flush=True)
    if dev.platform == "cpu":
        print("WARNING: CPU run — times are structural only, publish "
              "numbers from an idle TPU run", flush=True)

    # the bench --std config: 10-client cohort, cohort_groups=5 ->
    # grouped ops carry 2 clients; mean steps/round from the bench sim
    sys.argv = ["bench.py"]
    import bench

    sim = bench.build_sim(num_clients=100, model_name="resnet56")
    counts = np.asarray(sim.arrays.counts)
    mean_steps = float(np.mean(np.ceil(counts / sim.batch_size)))
    n_groups = sim.cfg.train.cohort_groups  # sequential sub-group passes
    group = sim.cfg.fed.clients_per_round // n_groups

    rows = []
    total_t = total_useful = total_executed = 0.0
    for (label, B, H, Cin, Cout, k, stride, fgc,
         per_pass) in conv_shapes(group=group, batch=sim.batch_size):
        t, executed, useful = bench_conv(B, H, Cin, Cout, k, stride, fgc)
        per_round = per_pass * mean_steps * n_groups
        expansion = (executed / useful) if executed and useful else None
        rows.append((label, B, H, fgc, t * 1e6, per_round,
                     t * per_round * 1e3, useful * per_round / 1e9,
                     (executed or 0) * per_round / 1e9, expansion))
        total_t += t * per_round
        total_useful += useful * per_round
        total_executed += (executed or 0) * per_round
        print(f"  {label}: {t*1e6:.0f} us/call x {per_round:.0f}", flush=True)

    # measured full round for the residual
    rps, _, _ = bench.rate_bench(sim, 6)
    round_s = 1.0 / rps
    peak = bench.PEAKS.get(dev.device_kind, (None, None))[0]

    lines = [
        "# Standard-layout ResNet-56 round: per-op ledger",
        "",
        f"Device: {dev.device_kind}; cohort 10 clients in {n_groups} "
        f"sub-groups of {group}; batch {sim.batch_size}; mean "
        f"{mean_steps:.1f} steps/client/round; measured round "
        f"{round_s*1e3:.1f} ms ({rps:.2f} r/s).",
        "",
        "| conv op | B | H | fgc | us/call | calls/round | ms/round | "
        "useful GFLOP | executed GFLOP | expansion |",
        "|---|---|---|---|---|---|---|---|---|---|",
    ]
    for (label, B, H, fgc, us, cnt, ms, ugf, egf, exp) in rows:
        lines.append(
            f"| {label} | {B} | {H} | {fgc} | {us:.0f} | {cnt:.0f} | "
            f"{ms:.2f} | {ugf:.1f} | {egf:.1f} | "
            f"{exp:.1f}x |" if exp else
            f"| {label} | {B} | {H} | {fgc} | {us:.0f} | {cnt:.0f} | "
            f"{ms:.2f} | {ugf:.1f} | — | — |"
        )
    conv_ms = total_t * 1e3
    resid_ms = round_s * 1e3 - conv_ms
    lines += [
        "",
        f"- conv ops account for **{conv_ms:.1f} ms** of the "
        f"{round_s*1e3:.1f} ms round ({100*conv_ms/round_s:.0f}%); "
        f"residual {resid_ms:.1f} ms = BN/elementwise/glue + per-round "
        "lowering latency.",
        f"- useful conv FLOPs {total_useful/1e9:.1f} GFLOP vs executed "
        f"{total_executed/1e9:.1f} GFLOP -> mean dense-expansion "
        f"{total_executed/max(total_useful,1):.1f}x.",
    ]
    if peak:
        ideal_ms = total_useful / peak * 1e3
        lines.append(
            f"- bounds: executed-op bound {conv_ms:.1f} ms/round "
            f"(= {1000/conv_ms:.1f} r/s ceiling at this lowering); "
            f"useful-FLOP ideal {ideal_ms:.2f} ms "
            f"(= {1000/ideal_ms:.0f} r/s) — unreachable without "
            "de-expanding 16-channel-per-client convs, which neither "
            "XLA nor a Pallas kernel can tile on a 128x128 MXU "
            "(docs/PERFORMANCE.md)."
        )
    out = "\n".join(lines) + "\n"
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "ledger_resnet56_std.md")
    with open(path, "w") as f:
        f.write(out)
    print(out)
    print(f"wrote {path}")


if __name__ == "__main__":
    main()

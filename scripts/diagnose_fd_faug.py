"""Diagnose whether FD(+FAug)'s knowledge-exchange term does anything.

VERDICT r4 weak #2: the battery showed FD+FAug == local-only baseline
(0.240 vs 0.240 at 50 rounds), indistinguishable from a dead KD path.
This script separates "faithfully weak method" from "silent bug" with
one instrumented run at the battery's partition shape:

1. teacher tensor vs uniform: max |softmax(teacher_row) - 1/K| — a dead
   exchange would leave softmax(zeros) = exactly uniform;
2. per-label teacher coverage (has_teacher fraction);
3. loss delta on one fixed batch with the KD term on vs off;
4. final mean client accuracy across kd_gamma in {0, 0.1(default), 0.5}.

Run: JAX_PLATFORMS=cpu python scripts/diagnose_fd_faug.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.distill import FDSim
from fedml_tpu.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    GanConfig,
    ModelConfig,
    TrainConfig,
)
from fedml_tpu.data.loaders import load_dataset
from fedml_tpu.models import create_model


def run(kd_gamma: float, rounds: int = 20):
    cfg = ExperimentConfig(
        # the battery shape (MNIST-like, 10 clients, hetero alpha=0.1)
        # on the fast `lr` model so the whole diagnosis runs in minutes
        data=DataConfig(dataset="fake_mnist", num_clients=10,
                        partition_method="hetero", partition_alpha=0.1,
                        batch_size=32, seed=0),
        model=ModelConfig(name="lr", num_classes=10,
                          input_shape=(28, 28, 1)),
        train=TrainConfig(lr=0.01, weight_decay=1e-3, epochs=5),
        fed=FedConfig(algorithm="fd_faug", num_rounds=rounds,
                      clients_per_round=10),
        gan=GanConfig(kd_gamma=kd_gamma),
        seed=0,
    )
    data = load_dataset(cfg.data)
    model = create_model(cfg.model)
    sim = FDSim(model, data, cfg)
    state = sim.init()
    for _ in range(rounds):
        state, _ = sim.run_round(state)
    accs = sim.evaluate_clients(state)
    return sim, state, accs


def main():
    results = {}
    for gamma in (0.0, 0.1, 0.5):
        sim, state, accs = run(gamma)
        mean_acc = float(accs["test_acc"])
        results[gamma] = (sim, state, mean_acc)
        print(f"kd_gamma={gamma}: mean client test acc {mean_acc:.4f}",
              flush=True)

    sim, state, _ = results[0.5]
    K = state.teacher.shape[-1]
    soft = jax.nn.softmax(state.teacher, axis=-1)
    dev = jnp.abs(soft - 1.0 / K)
    print(f"teacher max |softmax - uniform| = {float(dev.max()):.4f} "
          f"(dead exchange would be 0.0)")
    print(f"teacher coverage: {float(state.has_teacher.mean()):.3f} of "
          f"(client,label) pairs have a teacher")

    # loss with the KD term on vs off, same batch, same trained model
    arrays = sim.arrays
    mvars = jax.tree.map(lambda s: s[0], state.model_stack)
    xb = arrays.x[arrays.idx[0][:32]]
    yb = arrays.y[arrays.idx[0][:32]]
    wb = arrays.mask[0][:32]
    import optax

    logits = sim.model.apply_eval(mvars, xb)
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, yb)
    t_rows = state.teacher[0][yb]
    kd_ce = optax.softmax_cross_entropy(logits,
                                        jax.nn.softmax(t_rows, axis=-1))
    use = state.has_teacher[0][yb]
    for g in (0.0, 0.1, 0.5):
        gam = g * use
        loss = float(jnp.sum(((1 - gam) * ce + gam * kd_ce) * wb)
                     / jnp.maximum(jnp.sum(wb), 1.0))
        print(f"one-batch loss at gamma={g}: {loss:.5f}")
    print(f"mean |kd_ce - ce| on the batch: "
          f"{float(jnp.mean(jnp.abs(kd_ce - ce))):.5f}")


if __name__ == "__main__":
    main()

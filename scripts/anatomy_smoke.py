"""CI smoke: the round-anatomy plane end to end over a real gRPC world.

A 1-server + 2-client world runs with ``--anatomy`` on every rank and
``--metrics_port 0 --slo 'perf.round_wall_s:p99<0.3@2s'
--profile_on_breach --profile_max_captures 1`` on the server
(docs/OBSERVABILITY.md "Round anatomy"):

- client 2 runs under a seeded chaos delay (every message +up to
  0.8 s) and LEAVEs gracefully after round 3 — the induced slow phase
  that (a) makes rank 2 the dominant straggler and (b) breaches the
  tight SLO exactly once;
- mid-run the rank-0 ``/metrics`` endpoint must serve the server's
  ``perf.phase.*`` histograms AND the fleet-federated
  ``fleet.perf.phase.local_s`` (from the clients' own anatomy planes)
  through the strict OpenMetrics checks, and ``/tracez`` must serve the
  deploy anatomy ring as JSON;
- after the run: ``perf.straggler.rank2`` dominates ``rank1`` by no
  less than half the injected delay, phase attribution on every ring
  entry conserved to its wall, and EXACTLY ONE ``jax.profiler``
  artifact under ``<telemetry_dir>/profiles/`` whose ``breach.json``
  manifest links it to the SLO breach (``profile.captures == 1`` in the
  final metrics snapshot — the cap held).

Usage::

    python scripts/anatomy_smoke.py OUT_DIR
"""

from __future__ import annotations

import glob
import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

from slo_smoke import _check_exposition, _env, _free_ports, _scrape  # noqa: E402

ROUNDS = 200
LEAVE_AFTER = 3
TIGHT = "perf.round_wall_s:p99<0.3@2s"


def main(out_dir: str) -> int:
    os.makedirs(out_dir, exist_ok=True)
    cfg = {
        "data": {"dataset": "fake_mnist", "num_clients": 2,
                 "batch_size": 32, "partition_method": "homo",
                 "seed": 0},
        "model": {"name": "lr", "num_classes": 10,
                  "input_shape": [28, 28, 1]},
        "train": {"lr": 0.1, "epochs": 1},
        "fed": {"algorithm": "fedavg", "num_rounds": ROUNDS,
                "clients_per_round": 2, "eval_every": ROUNDS},
        "seed": 0,
        "run_name": "anatomy",
        "out_dir": out_dir,
    }
    cfg_path = os.path.join(out_dir, "cfg.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    ports = _free_ports(3)
    ip_path = os.path.join(out_dir, "ip.json")
    with open(ip_path, "w") as f:
        json.dump({str(r): ["127.0.0.1", ports[r]] for r in range(3)},
                  f)
    telemetry_dir = os.path.join(out_dir, "telemetry")
    base = [sys.executable, "-m", "fedml_tpu.experiments.run",
            "--config", cfg_path, "--backend", "grpc",
            "--world_size", "3", "--ip_config", ip_path,
            "--ready_timeout", "120",
            "--telemetry_dir", telemetry_dir,
            "--metrics_interval", "0.1",
            "--heartbeat_interval", "0.5", "--heartbeat_timeout", "30",
            "--quorum_fraction", "0.5", "--round_deadline", "120",
            "--anatomy"]
    env = _env()

    def spawn(role, rank=None, extra=()):
        argv = [*base, "--role", role, *extra]
        if rank is not None:
            argv += ["--rank", str(rank)]
        return subprocess.Popen(argv, env=env, cwd=REPO,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)

    procs = {
        # client 1: small pacing delay so fast rounds stay well under
        # the tight threshold while the post-breach tail drains
        1: spawn("client", 1, extra=("--fault_delay", "1.0",
                                     "--fault_delay_max", "0.03")),
        # client 2: the induced straggler AND slow phase — every
        # message +up to 0.8 s, graceful LEAVE after round 3
        2: spawn("client", 2, extra=("--fault_delay", "1.0",
                                     "--fault_delay_max", "0.8",
                                     "--leave_after_round",
                                     str(LEAVE_AFTER))),
    }
    server = spawn("server", extra=("--metrics_port", "0",
                                    "--slo", TIGHT,
                                    "--profile_on_breach",
                                    "--profile_window_s", "2",
                                    "--profile_max_captures", "1"))

    # -- discover the ephemeral port -------------------------------------
    export_path = os.path.join(telemetry_dir, "export_rank0.json")
    deadline = time.monotonic() + 240
    port = None
    while port is None and time.monotonic() < deadline:
        if server.poll() is not None:
            out = server.communicate()[0]
            for p in procs.values():
                p.kill()
            raise SystemExit(
                f"server exited rc={server.returncode} before the "
                f"exporter came up:\n{out}"
            )
        if os.path.exists(export_path):
            with open(export_path) as f:
                port = json.load(f)["port"]
        time.sleep(0.05)
    if port is None:
        server.kill()
        for p in procs.values():
            p.kill()
        raise SystemExit("export_rank0.json never appeared")

    # -- mid-run: phase vocabulary on /metrics, anatomy ring on /tracez ---
    types = tracez = None
    while time.monotonic() < deadline and server.poll() is None:
        code, metrics_text = _scrape(port, "/metrics")
        assert code == 200
        types = _check_exposition(metrics_text)
        if ("perf_phase_wire_s" in types
                and "fleet_perf_phase_local_s" in types):
            code, tz = _scrape(port, "/tracez")
            assert code == 200
            tracez = json.loads(tz)
            break
        time.sleep(0.2)
    assert types and types.get("perf_phase_wire_s") == "histogram", (
        f"server phase histograms never appeared "
        f"(types: {sorted(t for t in (types or {}))})"
    )
    assert types.get("fleet_perf_phase_local_s") == "histogram", (
        "clients' perf.phase.local_s never federated into fleet.*"
    )
    assert tracez is not None and tracez["entries"], tracez
    assert all(e["path"] == "deploy" for e in tracez["entries"])
    for e in tracez["entries"]:
        assert abs(sum(e["phases"].values()) - e["wall_s"]) <= 1e-9, e
        assert "host_gap" in e["phases"], e

    # -- wind down --------------------------------------------------------
    s_out = server.communicate(timeout=600)[0]
    outs = {}
    for r, p in procs.items():
        try:
            outs[r] = p.communicate(timeout=60)[0]
        except subprocess.TimeoutExpired:
            p.kill()
            outs[r] = p.communicate()[0]
    if server.returncode != 0:
        raise SystemExit(f"server failed rc={server.returncode}:\n{s_out}")
    # stderr is merged into stdout and the profiler's stop path may log
    # AFTER the summary line — take the last line that parses as JSON
    summary = None
    for line in reversed(s_out.strip().splitlines()):
        try:
            summary = json.loads(line)
            break
        except json.JSONDecodeError:
            continue
    assert isinstance(summary, dict) and "rounds" in summary, s_out[-2000:]
    assert summary["rounds"] == ROUNDS, summary
    assert summary["membership"]["left"] == [2], summary

    # -- straggler attribution names the delayed rank ---------------------
    with open(os.path.join(telemetry_dir, "metrics_rank0.json")) as f:
        metrics = json.load(f)
    g = metrics["gauges"]
    # gauges freeze at the last >=2-arrival round — inside the slow
    # phase, where rank 2's margin is the injected delay
    assert g["perf.straggler.rank2"] - g.get("perf.straggler.rank1", 0.0) \
        >= 0.05, g
    assert g["perf.critical_path_s"] > 0, g
    assert metrics["histograms"]["perf.straggler_wait_s"]["count"] >= 1
    assert metrics["histograms"]["perf.phase.wire_s"]["count"] >= ROUNDS

    # -- exactly one breach-profile artifact, linked by manifest ----------
    profiles = sorted(glob.glob(
        os.path.join(telemetry_dir, "profiles", "breach_*")
    ))
    assert len(profiles) == 1, (
        f"expected exactly one profile artifact, got {profiles}"
    )
    with open(os.path.join(profiles[0], "breach.json")) as f:
        manifest = json.load(f)
    assert manifest["reason"].startswith("slo_"), manifest
    assert manifest["capture"] == 1, manifest
    assert g["profile.active"] == 0.0, "capture window never closed"
    assert metrics["counters"]["profile.captures"] == 1, metrics["counters"]

    print(json.dumps({
        "anatomy_smoke": "ok",
        "rounds": summary["rounds"],
        "tracez_entries_at_scrape": len(tracez["entries"]),
        "straggler_rank2_margin_s": round(
            g["perf.straggler.rank2"]
            - g.get("perf.straggler.rank1", 0.0), 4,
        ),
        "profile_artifact": os.path.basename(profiles[0]),
        "breach_reason": manifest["reason"],
    }))
    return 0


if __name__ == "__main__":
    if len(sys.argv) != 2:
        raise SystemExit("usage: anatomy_smoke.py OUT_DIR")
    sys.exit(main(sys.argv[1]))

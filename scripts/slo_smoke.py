"""CI smoke: live export + SLO engine over a real gRPC world.

Drives the live observability plane end to end (docs/OBSERVABILITY.md
"Live export and SLOs"): a 1-server + 2-client gRPC world runs with
``--metrics_port 0`` and two SLOs —

- a LOOSE one (``perf.round_wall_s:p99<30@5s``) that must never breach;
- a TIGHT one (``perf.round_wall_s:p99<0.3@2s``) that the induced slow
  phase must breach EXACTLY ONCE: client 2 runs under a seeded chaos
  delay (every message +0.005..0.8 s) for its whole stay and LEAVEs
  gracefully after round 3, so rounds 0..3 are slow (round 0's client
  jit compile adds more), every later round is fast, and the tight
  SLO's ok gauge flips 1 -> 0 -> 1 with one breach transition and a
  recorded breach duration.

Mid-run the script scrapes rank 0's ephemeral ``/metrics`` endpoint
(port discovered from ``export_rank0.json``) and asserts the exposition
parses — ``# TYPE`` lines, monotone cumulative buckets — and carries
``fleet.*`` aggregates federated from the clients' heartbeat
piggybacks; ``/statusz`` must report the live round and ``/healthz``
must be 200. After the run, ``slo_rank0.json`` must hold the verdicts:
loose ok with zero transitions, tight ok with exactly two transitions
(breach + recovery) and breach_seconds > 0 — and the metrics
time-series must show exactly one contiguous breached block.

Usage::

    python scripts/slo_smoke.py OUT_DIR
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ROUNDS = 300
LEAVE_AFTER = 3
TIGHT = "perf.round_wall_s:p99<0.3@2s"
LOOSE = "perf.round_wall_s:p99<30@5s"


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _scrape(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return r.status, r.read().decode()


def _check_exposition(text):
    """Minimal strict checks mirroring tests/test_export.py's parser:
    every sample's family has a # TYPE line; every histogram's bucket
    series is cumulative-monotone and +Inf-terminated."""
    types, buckets = {}, {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            name, kind = line[len("# TYPE "):].split(" ", 1)
            types[name] = kind
            continue
        assert not line.startswith("#"), f"unknown comment: {line!r}"
        name, _, value = line.partition(" ")
        base = name.split("{", 1)[0]
        fam = base
        for suffix in ("_bucket", "_sum", "_count", "_p50", "_p95",
                       "_p99"):
            if base.endswith(suffix) and base[:-len(suffix)] in types:
                fam = base[:-len(suffix)]
        assert fam in types, f"sample {name!r} has no # TYPE"
        if base.endswith("_bucket"):
            le = name.split('le="', 1)[1].split('"', 1)[0]
            buckets.setdefault(base, []).append(
                (float("inf") if le == "+Inf" else float(le),
                 float(value))
            )
    # (an early scrape may legitimately predate any histogram; bucket
    # SHAPE is validated whenever buckets are present, and the accept
    # loop below only finishes once the fleet histogram exists)
    for name, series in buckets.items():
        les = [le for le, _ in series]
        counts = [c for _, c in series]
        assert les == sorted(les), f"{name} out of order"
        assert counts == sorted(counts), f"{name} not cumulative"
        assert les[-1] == float("inf"), f"{name} missing +Inf"
    return types


def main(out_dir: str) -> int:
    os.makedirs(out_dir, exist_ok=True)
    cfg = {
        "data": {"dataset": "fake_mnist", "num_clients": 2,
                 "batch_size": 32, "partition_method": "homo",
                 "seed": 0},
        "model": {"name": "lr", "num_classes": 10,
                  "input_shape": [28, 28, 1]},
        "train": {"lr": 0.1, "epochs": 1},
        "fed": {"algorithm": "fedavg", "num_rounds": ROUNDS,
                "clients_per_round": 2, "eval_every": ROUNDS},
        "seed": 0,
        "run_name": "slo",
        "out_dir": out_dir,
    }
    cfg_path = os.path.join(out_dir, "cfg.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    ports = _free_ports(3)
    ip_path = os.path.join(out_dir, "ip.json")
    with open(ip_path, "w") as f:
        json.dump({str(r): ["127.0.0.1", ports[r]] for r in range(3)},
                  f)
    telemetry_dir = os.path.join(out_dir, "telemetry")
    base = [sys.executable, "-m", "fedml_tpu.experiments.run",
            "--config", cfg_path, "--backend", "grpc",
            "--world_size", "3", "--ip_config", ip_path,
            "--ready_timeout", "120",
            "--telemetry_dir", telemetry_dir,
            "--metrics_interval", "0.1",
            "--heartbeat_interval", "0.5", "--heartbeat_timeout", "30",
            "--quorum_fraction", "0.5", "--round_deadline", "120"]
    env = _env()

    def spawn(role, rank=None, extra=()):
        argv = [*base, "--role", role, *extra]
        if rank is not None:
            argv += ["--rank", str(rank)]
        return subprocess.Popen(argv, env=env, cwd=REPO,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)

    procs = {
        # client 1 carries a small pacing delay (5..30 ms per message):
        # fast rounds stay far below the tight threshold while giving
        # the post-breach tail enough wall time to drain the window
        1: spawn("client", 1, extra=("--fault_delay", "1.0",
                                     "--fault_delay_max", "0.03")),
        # client 2 is the induced slow phase: every message +up to
        # 0.8 s, then a graceful LEAVE — after it departs every round
        # is fast and the tight SLO must recover
        2: spawn("client", 2, extra=("--fault_delay", "1.0",
                                     "--fault_delay_max", "0.8",
                                     "--leave_after_round",
                                     str(LEAVE_AFTER))),
    }
    server = spawn("server", extra=("--metrics_port", "0",
                                    "--slo", TIGHT, "--slo", LOOSE))

    # -- discover the ephemeral port, scrape mid-run -----------------------
    export_path = os.path.join(telemetry_dir, "export_rank0.json")
    deadline = time.monotonic() + 240
    port = None
    while port is None and time.monotonic() < deadline:
        if server.poll() is not None:
            out = server.communicate()[0]
            for p in procs.values():
                p.kill()
            raise SystemExit(
                f"server exited rc={server.returncode} before the "
                f"exporter came up:\n{out}"
            )
        if os.path.exists(export_path):
            with open(export_path) as f:
                port = json.load(f)["port"]
        time.sleep(0.05)
    if port is None:
        server.kill()
        for p in procs.values():
            p.kill()
        raise SystemExit("export_rank0.json never appeared")

    # the fleet aggregates need at least one client heartbeat summary;
    # poll the live endpoint until they land (mid-run by construction:
    # the run lasts hundreds of rounds)
    fleet_seen = live_round = None
    slo_block = healthz = None
    while time.monotonic() < deadline and server.poll() is None:
        code, metrics_text = _scrape(port, "/metrics")
        assert code == 200
        types = _check_exposition(metrics_text)
        code, statusz_text = _scrape(port, "/statusz")
        assert code == 200
        statusz = json.loads(statusz_text)
        if "server" in statusz:
            live_round = statusz["server"]["round"]
        slo_block = statusz.get("slo")
        code, hz = _scrape(port, "/healthz")
        healthz = (code, json.loads(hz))
        if ("fleet_perf_round_wall_s" in types
                and "perf_round_wall_s" in types
                and live_round is not None):
            fleet_seen = types["fleet_perf_round_wall_s"]
            break
        time.sleep(0.2)
    assert fleet_seen == "histogram", (
        f"fleet.* client aggregates never appeared on /metrics "
        f"(types: {sorted(t for t in (types or {}))})"
    )
    assert "perf_round_wall_s" in types, sorted(types)
    assert live_round is not None and live_round >= 0
    assert slo_block and {s["metric"] for s in slo_block} == {
        "perf.round_wall_s"
    }, slo_block
    assert healthz[0] == 200 and healthz[1]["status"] == "ok", healthz

    # -- wind down ---------------------------------------------------------
    s_out = server.communicate(timeout=600)[0]
    outs = {}
    for r, p in procs.items():
        try:
            outs[r] = p.communicate(timeout=60)[0]
        except subprocess.TimeoutExpired:
            p.kill()
            outs[r] = p.communicate()[0]
    if server.returncode != 0:
        raise SystemExit(
            f"server failed rc={server.returncode}:\n{s_out}"
        )
    summary = json.loads(s_out.strip().splitlines()[-1])
    assert summary["rounds"] == ROUNDS, summary
    assert summary["membership"]["left"] == [2], summary

    # -- the SLO verdicts --------------------------------------------------
    with open(os.path.join(telemetry_dir, "slo_rank0.json")) as f:
        verdicts = {v["slo"]: v for v in json.load(f)["slos"]}
    tight = next(v for k, v in verdicts.items() if "0.3" in k)
    loose = next(v for k, v in verdicts.items() if "30" in k)
    assert loose["ok"] and loose["transitions"] == 0, loose
    assert tight["ok"], tight
    # exactly one breach TRANSITION (and its recovery)
    assert tight["transitions"] == 2, tight
    assert tight["breach_seconds"] > 0, tight

    # -- slo.ok 1 -> 0 -> 1, exactly one contiguous breached block ---------
    key = None
    series = []
    with open(os.path.join(telemetry_dir,
                           "metrics_rank0.jsonl")) as f:
        for line in f:
            row = json.loads(line)
            if key is None:
                key = next((k for k in row.get("gauges", {})
                            if k.startswith("slo.ok.")
                            and row["gauges"][k] is not None), None)
            if key and key in row.get("gauges", {}):
                series.append(row["gauges"][key])
    # collapse consecutive duplicates: the tight SLO's trajectory must
    # be exactly one breached block — [1,0,1] (or [0,1] when the first
    # tick already saw the slow phase)
    dedup = [series[0]] if series else []
    for v in series[1:]:
        if v != dedup[-1]:
            dedup.append(v)
    assert dedup in ([1.0, 0.0, 1.0], [0.0, 1.0]), dedup

    print(json.dumps({
        "slo_smoke": "ok",
        "rounds": summary["rounds"],
        "live_round_at_scrape": live_round,
        "tight": {"transitions": tight["transitions"],
                  "breach_seconds": tight["breach_seconds"]},
        "ok_trajectory": dedup,
    }))
    return 0


if __name__ == "__main__":
    if len(sys.argv) != 2:
        raise SystemExit("usage: slo_smoke.py OUT_DIR")
    sys.exit(main(sys.argv[1]))

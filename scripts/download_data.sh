#!/usr/bin/env bash
# Dataset fetcher for fedml_tpu's real-file loaders.
#
# Mirrors the reference's per-dataset download scripts
# (reference: data/<ds>/download_*.sh — e.g. data/MNIST/
# download_and_unzip.sh, data/fed_shakespeare/download_shakespeare.sh,
# data/stackoverflow/download_stackoverflow.sh, data/gld/
# download_from_aws_s3.sh, data/edge_case_examples/download_*.sh) as ONE
# dispatcher: `./scripts/download_data.sh <dataset> [dest_dir]`.
#
# The loaders in fedml_tpu/data/{loaders,natural,largescale,vertical}.py
# read the exact on-disk formats these sources provide (IDX, CIFAR pickle
# batches, TFF h5, LEAF json, GLD CSV splits, UCI csv). Environments
# without egress (like the build/bench hosts) use the procedural fake_*
# datasets instead; every loader falls back with a pointer to this script.
set -euo pipefail

DS="${1:-help}"
DEST="${2:-${FEDML_TPU_DATA:-$HOME/.fedml_tpu/data}}"

fetch() { # fetch <url> <out-file>
  mkdir -p "$(dirname "$2")"
  if command -v curl >/dev/null; then
    curl -fL --retry 3 -o "$2" "$1"
  else
    wget -O "$2" "$1"
  fi
}

gdrive() { # gdrive <file-id> <out-file>  (large-file confirm dance)
  local id="$1" out="$2"
  mkdir -p "$(dirname "$out")"
  local base="https://docs.google.com/uc?export=download"
  local jar confirm
  jar=$(mktemp)
  if command -v curl >/dev/null; then
    confirm=$(curl -sc "$jar" "${base}&id=${id}" \
      | sed -rn 's/.*confirm=([0-9A-Za-z_]+).*/\1/p' || true)
    curl -fLb "$jar" -o "$out" "${base}&confirm=${confirm}&id=${id}"
  else
    confirm=$(wget -q --save-cookies "$jar" --keep-session-cookies \
      "${base}&id=${id}" -O- \
      | sed -rn 's/.*confirm=([0-9A-Za-z_]+).*/\1/p' || true)
    wget --load-cookies "$jar" -O "$out" "${base}&confirm=${confirm}&id=${id}"
  fi
  rm -f "$jar"
}

untar() { mkdir -p "$2" && tar -xf "$1" -C "$2"; }

case "$DS" in
mnist)
  # reference data/MNIST/download_and_unzip.sh (Google Drive zip of IDX files)
  gdrive 1cU_LcBAUZvfZWveOMhG4G5Fg9uFXhVdf "$DEST/mnist/MNIST.zip"
  (cd "$DEST/mnist" && unzip -o MNIST.zip && rm -f MNIST.zip)
  ;;
cifar10)
  fetch https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz \
    "$DEST/cifar10.tar.gz"
  untar "$DEST/cifar10.tar.gz" "$DEST/cifar10"
  ;;
cifar100)
  fetch https://www.cs.toronto.edu/~kriz/cifar-100-python.tar.gz \
    "$DEST/cifar100.tar.gz"
  untar "$DEST/cifar100.tar.gz" "$DEST/cifar100"
  ;;
cinic10)
  fetch https://datashare.is.ed.ac.uk/bitstream/handle/10283/3192/CINIC-10.tar.gz \
    "$DEST/cinic10.tar.gz"
  untar "$DEST/cinic10.tar.gz" "$DEST/cinic10"
  ;;
fed_emnist | federated_emnist)
  # TFF h5 natural split (reference data/FederatedEMNIST)
  fetch https://fedml.s3-us-west-1.amazonaws.com/fed_emnist.tar.bz2 \
    "$DEST/fed_emnist.tar.bz2"
  untar "$DEST/fed_emnist.tar.bz2" "$DEST/fed_emnist"
  ;;
fed_cifar100)
  fetch https://fedml.s3-us-west-1.amazonaws.com/fed_cifar100.tar.bz2 \
    "$DEST/fed_cifar100.tar.bz2"
  untar "$DEST/fed_cifar100.tar.bz2" "$DEST/fed_cifar100"
  ;;
fed_shakespeare | shakespeare)
  # TFF h5 char-LM split (reference data/fed_shakespeare)
  fetch https://fedml.s3-us-west-1.amazonaws.com/shakespeare.tar.bz2 \
    "$DEST/shakespeare.tar.bz2"
  untar "$DEST/shakespeare.tar.bz2" "$DEST/shakespeare"
  ;;
stackoverflow)
  # nwp + lr share the corpus; tag/word count vocab files ride along
  for f in stackoverflow.tar.bz2 stackoverflow.tag_count.tar.bz2 \
    stackoverflow.word_count.tar.bz2; do
    fetch "https://fedml.s3-us-west-1.amazonaws.com/$f" "$DEST/$f"
    untar "$DEST/$f" "$DEST/stackoverflow"
  done
  ;;
landmarks | gld)
  # Google Landmarks federated splits (reference data/gld/download_from_aws_s3.sh)
  fetch https://fedcv.s3-us-west-1.amazonaws.com/landmark/data_user_dict.zip \
    "$DEST/landmarks/data_user_dict.zip"
  fetch https://fedcv.s3-us-west-1.amazonaws.com/landmark/images.zip \
    "$DEST/landmarks/images.zip"
  (cd "$DEST/landmarks" && unzip -o data_user_dict.zip && unzip -o images.zip)
  ;;
edge_case_examples)
  # curated backdoor sets (reference data/edge_case_examples)
  fetch http://pages.cs.wisc.edu/~hongyiwang/edge_case_attack/edge_case_examples.zip \
    "$DEST/edge_case_examples.zip"
  (cd "$DEST" && unzip -o edge_case_examples.zip)
  ;;
susy)
  # UCI SUSY for streaming decentralized online learning (reference data/UCI/SUSY)
  fetch https://archive.ics.uci.edu/ml/machine-learning-databases/00279/SUSY.csv.gz \
    "$DEST/uci/SUSY.csv.gz"
  gunzip -kf "$DEST/uci/SUSY.csv.gz"
  ;;
room_occupancy)
  fetch https://archive.ics.uci.edu/ml/machine-learning-databases/00357/occupancy_data.zip \
    "$DEST/uci/occupancy_data.zip"
  (cd "$DEST/uci" && unzip -o occupancy_data.zip)
  ;;
synthetic)
  echo "synthetic(alpha,beta) is generated procedurally:" >&2
  echo "  load_dataset(DataConfig(dataset='synthetic_1_1', ...))" >&2
  echo "No download needed (reference data/synthetic_*/generate_synthetic.py)." >&2
  ;;
help | *)
  cat >&2 <<'USAGE'
usage: scripts/download_data.sh <dataset> [dest_dir]

datasets: mnist cifar10 cifar100 cinic10 fed_emnist fed_cifar100
          fed_shakespeare stackoverflow landmarks edge_case_examples
          susy room_occupancy synthetic

dest_dir defaults to $FEDML_TPU_DATA or ~/.fedml_tpu/data. Point the
loaders at the same path via DataConfig(data_dir=...).
USAGE
  [ "$DS" = help ] || exit 1
  ;;
esac

"""Merge per-rank tracer dumps into ONE Chrome-trace-event JSON.

Each deployment rank (or a shared-process loopback world) dumps its
:class:`~fedml_tpu.core.tracing.Tracer` events to
``<telemetry_dir>/trace_rank<r>.json``. This tool folds any number of
those dumps into a single Chrome trace-event file — load it at
https://ui.perfetto.dev (or chrome://tracing) and every rank appears as
its own process (pid = rank), with threads as tracks and cross-process
flow arrows connecting a message's ``msg_send`` on the sending rank to
its ``msg_deliver`` on the receiving rank (matched by the span id the
:class:`~fedml_tpu.core.message.Message` envelope carried over the
wire; docs/OBSERVABILITY.md).

Usage::

    python scripts/merge_trace.py RUN_TELEMETRY_DIR [--out merged.json]
    python scripts/merge_trace.py trace_rank0.json trace_rank1.json ...
    python scripts/merge_trace.py RUN_TELEMETRY_DIR --jax-profile

Timestamps are wall-clock (epoch) microseconds rebased to the earliest
event, so ranks on the same host line up; ``X`` complete events carry
span durations, instant events render as markers.

jax-profiler captures (``--profile_rounds``, core/perf.py) live in
their own files by design — ``<telemetry_dir>/jax_profile/round<k>/``,
one session per profiled round — so they can never clobber the host
span dumps, and ``--trace_jax`` annotations land INSIDE the capture
they belong to. ``--jax-profile`` optionally folds those captures into
the merged timeline: each profiled round becomes its own Perfetto
process (``jax profile round <k>``) holding the XLA op events, rebased
onto the host timeline via the epoch anchor in each capture's
``capture.json`` manifest (written at ``start_trace`` time — alignment
is anchor-accurate to ~ms, good enough to see which host span a device
burst belongs to; within-capture relative timing is exact).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def load_rank_events(path: str) -> list[dict]:
    """Read one tracer dump; tolerates both the current
    ``{"rank": r, "events": [...]}`` shape and a bare legacy list."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, list):
        events, default_rank = data, None
    else:
        events, default_rank = data.get("events", []), data.get("rank")
    out = []
    for ev in events:
        ev = dict(ev)
        if ev.get("rank") is None:
            ev["rank"] = default_rank if default_rank is not None else 0
        out.append(ev)
    return out


def _flow_id(span_id: str) -> int:
    try:
        return int(span_id, 16) & 0x7FFFFFFF
    except (TypeError, ValueError):
        return hash(span_id) & 0x7FFFFFFF


_STRUCTURAL = ("kind", "ts", "seconds", "rank", "tid", "name")


def merge(paths: list[str]) -> dict:
    """Fold tracer dumps into a Chrome trace-event dict.

    A supervised deployment (docs/FAULT_TOLERANCE.md "Recovery") leaves
    MULTIPLE dumps per rank — ``trace_rank<r>.json`` from the first
    incarnation, ``trace_rank<r>_i<n>.json`` from each restart. All of a
    rank's incarnations fold into the same pid (events carry their
    rank), so the timeline shows the crash gap and the resumed work on
    one track. A dump a SIGKILLed process left unreadable is skipped
    with a warning rather than sinking the merge."""
    events: list[dict] = []
    for p in paths:
        try:
            events.extend(load_rank_events(p))
        except (json.JSONDecodeError, OSError, KeyError, TypeError) as e:
            print(f"warning: skipping unreadable dump {p!r}: {e}",
                  file=sys.stderr)
    if not events:
        return {"traceEvents": [], "displayTimeUnit": "ms",
                "_epoch0": None}
    ts0 = min(float(ev.get("ts", 0.0)) for ev in events)

    trace_events: list[dict] = []
    ranks: set[int] = set()
    sends: dict[str, dict] = {}
    delivers: dict[str, dict] = {}

    cp_events: list[dict] = []

    for ev in events:
        rank = int(ev["rank"] or 0)
        ranks.add(rank)
        ts_us = (float(ev.get("ts", ts0)) - ts0) * 1e6
        dur_us = float(ev.get("seconds", 0.0)) * 1e6
        name = ev.get("name") or ev["kind"]
        if name == "critical_path":
            # round-anatomy critical path (core/anatomy.py): the
            # instant event carries the closed round's segment
            # durations — rendered as contiguous spans on a dedicated
            # track below, not a zero-width marker buried in rank 0's
            # stream
            cp_events.append(ev)
            continue
        args = {k: v for k, v in ev.items() if k not in _STRUCTURAL}
        base = {
            "name": name,
            "cat": ev["kind"],
            "pid": rank,
            "tid": int(ev.get("tid", 0)),
            "ts": ts_us,
            "args": args,
        }
        if dur_us > 0:
            trace_events.append({**base, "ph": "X", "dur": dur_us})
        else:
            trace_events.append({**base, "ph": "i", "s": "t"})
        span_id = ev.get("span_id")
        if span_id:
            if name == "msg_send":
                sends[span_id] = base
            elif name == "msg_deliver":
                delivers[span_id] = base

    # flow arrows: one per message observed on BOTH sides
    for span_id, send in sends.items():
        recv = delivers.get(span_id)
        if recv is None:
            continue
        fid = _flow_id(span_id)
        common = {"name": "msg", "cat": "msg_flow", "id": fid}
        trace_events.append({
            **common, "ph": "s", "pid": send["pid"], "tid": send["tid"],
            "ts": send["ts"],
        })
        trace_events.append({
            **common, "ph": "f", "bp": "e", "pid": recv["pid"],
            "tid": recv["tid"],
            # a deliver observed at (or clock-skewed before) its send
            # still needs flow ts >= the start or the arrow is dropped
            "ts": max(recv["ts"], send["ts"] + 1.0),
        })

    trace_events.extend(_critical_path_track(cp_events, ts0))

    for r in sorted(ranks):
        label = f"rank {r}" + (" (server)" if r == 0 else "")
        trace_events.append({
            "ph": "M", "name": "process_name", "pid": r, "tid": 0,
            "args": {"name": label},
        })
        trace_events.append({
            "ph": "M", "name": "process_sort_index", "pid": r, "tid": 0,
            "args": {"sort_index": r},
        })

    # _epoch0 (the epoch-seconds base every ts was rebased against) is
    # internal plumbing for fold_jax_profiles; stripped before writing
    return {"traceEvents": trace_events, "displayTimeUnit": "ms",
            "_epoch0": ts0}


#: synthetic pid for the round-anatomy critical-path track (above any
#: real rank, below the jax-profile block)
_CRITICAL_PATH_PID = 8000


def _critical_path_track(cp_events: list[dict], ts0: float) -> list[dict]:
    """Per-round critical-path spans (core/anatomy.py
    ``attribute_stragglers``): each ``critical_path`` instant event is
    emitted at round close and carries the closed round's segment
    durations, so the track reconstructs the dependent chain backwards
    from the close timestamp — ``sync -> slowest result (rank r)``
    followed by ``aggregate`` — as contiguous ``X`` spans on one
    synthetic process. Empty input (anatomy off, sim-only worlds)
    yields no track at all."""
    out: list[dict] = []
    for ev in cp_events:
        close_ts = float(ev.get("ts", ts0))
        closed_after = float(ev.get("closed_after_s", 0.0))
        sync_to_result = float(ev.get("sync_to_result_s", 0.0))
        agg = float(ev.get("aggregate_s", 0.0))
        # the event fires at close; the round's sync broadcast was
        # closed_after_s earlier
        start_us = (close_ts - ts0 - closed_after) * 1e6
        rnd = ev.get("round")
        rank_path = ev.get("rank_path")
        out.append({
            "name": f"r{rnd} sync->result rank{rank_path}",
            "cat": "critical_path",
            "ph": "X",
            "pid": _CRITICAL_PATH_PID,
            "tid": 0,
            "ts": start_us,
            "dur": sync_to_result * 1e6,
            "args": {
                "round": rnd,
                "rank_path": rank_path,
                "straggler_wait_s": ev.get("straggler_wait_s"),
                "total_s": ev.get("total_s"),
            },
        })
        if agg > 0:
            out.append({
                "name": f"r{rnd} aggregate",
                "cat": "critical_path",
                "ph": "X",
                "pid": _CRITICAL_PATH_PID,
                "tid": 0,
                "ts": start_us + sync_to_result * 1e6,
                "dur": agg * 1e6,
                "args": {"round": rnd},
            })
    if out:
        out.append({
            "ph": "M", "name": "process_name",
            "pid": _CRITICAL_PATH_PID, "tid": 0,
            "args": {"name": "critical path (round anatomy)"},
        })
        out.append({
            "ph": "M", "name": "process_sort_index",
            "pid": _CRITICAL_PATH_PID, "tid": 0,
            "args": {"sort_index": _CRITICAL_PATH_PID},
        })
    return out


#: pid block for folded jax-profile rounds (far above any real rank)
_JAX_PID_BASE = 9000


def fold_jax_profiles(merged: dict, dirs: list[str]) -> int:
    """Fold ``jax_profile/round<k>/`` captures (core/perf.py
    RoundProfiler) into an already-merged Chrome trace, one synthetic
    process per profiled round. Only XLA op events (those carrying an
    ``hlo_op`` arg or living on a ``/device:*`` plane) are folded — the
    captures also hold thousands of threadpool bookkeeping events that
    would bury the timeline. Returns the number of folded events."""
    try:
        from fedml_tpu.core.perf import load_trace_events
    except ImportError:
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        from fedml_tpu.core.perf import load_trace_events

    evs = merged["traceEvents"]
    host_ts0_us = min(
        (e["ts"] for e in evs if e.get("ph") in ("X", "i")),
        default=None,
    )
    # the host events were rebased to their earliest epoch; recover the
    # epoch base from the merge (merge() rebased by ts0 — stash it)
    epoch0 = merged.get("_epoch0")
    folded = 0
    for d in dirs:
        for rdir in sorted(glob.glob(os.path.join(d, "jax_profile",
                                                  "round*"))):
            manifest_path = os.path.join(rdir, "capture.json")
            try:
                with open(manifest_path) as f:
                    manifest = json.load(f)
            except (OSError, json.JSONDecodeError):
                print(f"warning: no capture manifest in {rdir!r}; "
                      "skipping", file=sys.stderr)
                continue
            rnd = manifest.get("round", 0)
            pid = _JAX_PID_BASE + int(rnd)
            # rebase: event ts is session-relative; the manifest's
            # t_start anchors the session on the epoch timeline
            if epoch0 is not None:
                base_us = (manifest["t_start"] - epoch0) * 1e6
            else:
                base_us = host_ts0_us or 0.0
            n = 0
            for ev in load_trace_events(rdir):
                if ("hlo_op" not in ev["args"]
                        and not ev["process"].startswith("/device:")):
                    continue
                evs.append({
                    "name": ev["name"],
                    "cat": "jax_op",
                    "ph": "X",
                    "pid": pid,
                    "tid": ev["tid"],
                    "ts": base_us + ev["ts"],
                    "dur": ev["dur"],
                    "args": ev["args"],
                })
                n += 1
            if n:
                evs.append({
                    "ph": "M", "name": "process_name", "pid": pid,
                    "tid": 0,
                    "args": {"name": f"jax profile round {rnd}"},
                })
                evs.append({
                    "ph": "M", "name": "process_sort_index", "pid": pid,
                    "tid": 0, "args": {"sort_index": pid},
                })
            folded += n
    return folded


def resolve_inputs(inputs: list[str]) -> list[str]:
    paths: list[str] = []
    for inp in inputs:
        if os.path.isdir(inp):
            found = sorted(glob.glob(os.path.join(inp, "trace_rank*.json")))
            if not found:
                raise SystemExit(f"no trace_rank*.json dumps in {inp!r}")
            paths.extend(found)
        else:
            paths.append(inp)
    return paths


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="merge per-rank tracer dumps into one Perfetto-"
                    "loadable Chrome trace (pid = rank)"
    )
    p.add_argument("inputs", nargs="+",
                   help="telemetry dir(s) and/or trace_rank*.json files")
    p.add_argument("--out", default=None,
                   help="output path (default: merged_trace.json next to "
                        "the first input)")
    p.add_argument("--jax-profile", action="store_true",
                   help="also fold jax-profiler captures "
                        "(<dir>/jax_profile/round*/ from "
                        "--profile_rounds) into the timeline, one "
                        "Perfetto process per profiled round")
    a = p.parse_args(argv)
    paths = resolve_inputs(a.inputs)
    merged = merge(paths)
    if a.jax_profile:
        dirs = [d for d in a.inputs if os.path.isdir(d)]
        n_jax = fold_jax_profiles(merged, dirs)
        print(f"folded {n_jax} jax-profile op events", file=sys.stderr)
    merged.pop("_epoch0", None)
    out = a.out
    if out is None:
        anchor = a.inputs[0]
        base = anchor if os.path.isdir(anchor) else os.path.dirname(anchor)
        out = os.path.join(base or ".", "merged_trace.json")
    with open(out, "w") as f:
        json.dump(merged, f)
    n = len(merged["traceEvents"])
    print(f"wrote {out}: {n} trace events from {len(paths)} dump(s)",
          file=sys.stderr)
    print(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())

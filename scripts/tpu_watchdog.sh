#!/bin/sh
# Probe the TPU tunnel every 5 min; when it answers, immediately run the
# 5-repetition battery (VERDICT r5 item 2) on it, then exit. The probe
# runs in a subprocess with a hard timeout because a wedged tunnel blocks
# jax backend init indefinitely.
#
# DEADLINE=<epoch seconds> (optional): never START the battery after
# this time — the tunnel admits one client at a time, so a battery
# straddling the driver's end-of-round bench would block it.
#
# Bench honesty (ROADMAP item 5, docs/PERFORMANCE.md "Bench
# trustworthiness"): a watchdog that gives up must NEVER leave the
# round with nothing — on deadline it runs `bench.py --fallback-only`,
# which appends the marked CPU-fallback record (+ one small labeled
# CPU measurement) to runs/bench_latest.jsonl, so the BENCH artifact
# says "tunnel was dead" in data instead of an empty rc=1.
cd "$(dirname "$0")/.."
while :; do
  if [ -n "${DEADLINE:-}" ] && [ "$(date +%s)" -gt "$DEADLINE" ]; then
    echo "$(date +%H:%M:%S) deadline passed; emitting marked CPU-fallback record"
    JAX_PLATFORMS=cpu python bench.py --fallback-only
    exit 1
  fi
  if timeout 120 python -c "
import jax, jax.numpy as jnp
assert jax.devices()[0].platform != 'cpu'
print(float(jnp.ones(8).sum()))" >/dev/null 2>&1; then
    echo "$(date +%H:%M:%S) TPU is back; starting 5-rep battery"
    rm -rf runs/battery_r5
    python scripts/run_battery.py --reps 5 --out runs/battery_r5 \
      > runs/battery_r5.log 2>&1
    echo "$(date +%H:%M:%S) battery finished rc=$?"
    exit 0
  fi
  echo "$(date +%H:%M:%S) TPU still unreachable"
  sleep 300
done

"""Microbench the cohort-grouped s2d step internals: conv trunk vs BN vs
layouts, per-op grouped conv rates, and sub-cohort scaling (C=5 vs C=10).
"""
from __future__ import annotations

import sys

import jax
import jax.numpy as jnp
from jax import lax

sys.path.insert(0, str(__import__("pathlib").Path(__file__).resolve().parent.parent))

# ONE timing path: the shared fetch-corrected amortized loop from the
# round-anatomy plane (this script used to carry its own drifting copy)
from fedml_tpu.core.anatomy import fetch_corrected_time as timeit


def conv_flops(B, H, W, k, ci, co):
    return 2 * B * H * W * k * k * ci * co


def bench_grouped_conv(B, H, W, cpg, C, k=3, n=40):
    """One grouped conv fwd+bwd (dw+dx via grad) at given shape."""
    ci = cpg * C
    x = jnp.ones((B, H, W, ci), jnp.bfloat16) * 0.01
    w = jnp.ones((k, k, cpg, ci), jnp.bfloat16) * 0.01

    def loss(x, w):
        y = lax.conv_general_dilated(
            x, w, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=C,
        )
        return jnp.sum(y.astype(jnp.float32) ** 2)

    g = jax.jit(jax.grad(loss, argnums=(0, 1)))
    t = timeit(lambda: g(x, w), n=n)
    fl = 3 * conv_flops(B, H, W, k, cpg, cpg) * C  # useful fwd+dx+dw
    return t, fl / t / 1e12


def main():
    print("== grouped conv fwd+bwd rates (useful TFLOP/s, v5e peak 197) ==")
    for (B, H, W, cpg, C, tag) in [
        (32, 16, 16, 64, 10, "s2d stage1"),
        (32, 16, 16, 32, 10, "s2d stage2"),
        (32, 8, 8, 64, 10, "s2d stage3"),
        (32, 16, 16, 64, 1, "dense 64 (1 client)"),
        (32, 16, 16, 640, 1, "dense 640 (shared-floor)"),
        (32, 32, 32, 16, 10, "std stage1 (16cpg)"),
    ]:
        t, r = bench_grouped_conv(B, H, W, cpg, C)
        print(f"{tag:24s} t={t*1e3:7.3f} ms useful={r:6.2f} TF/s "
              f"mfu={r/197*100:5.1f}%")

    # full fat-model grad with and without BN
    from fedml_tpu.models import create_model
    from fedml_tpu.config import ModelConfig

    for C in (10, 5):
        for extra, tag in [((), "bn"), ((("norm", "gn"),), "gn")]:
            cfgm = ModelConfig(
                name="resnet56_s2d", num_classes=10,
                input_shape=(32, 32, 3), extra=extra,
            )
            try:
                model = create_model(cfgm)
            except Exception as e:
                print("skip", tag, e)
                continue
            variables = model.init(jax.random.key(0))
            stacked = jax.tree.map(
                lambda v: jnp.broadcast_to(v[None], (C,) + v.shape) + 0.0,
                variables,
            )
            x_cb = jnp.ones((C, 32, 32, 32, 3), jnp.bfloat16) * 0.1

            def loss_fn(sp, ss, x):
                from fedml_tpu.algorithms.base import (
                    _tree_to_dtype, _static_vars_to_dtype,
                )
                var = {
                    **_static_vars_to_dtype(ss, jnp.bfloat16),
                    "params": _tree_to_dtype(sp, jnp.bfloat16),
                }
                logits, new_vars = model.apply_cohort_train(
                    var, x, jax.random.key(0)
                )
                return jnp.sum(logits.astype(jnp.float32) ** 2), new_vars

            sp = stacked["params"]
            ss = {k: v for k, v in stacked.items() if k != "params"}
            g = jax.jit(jax.grad(loss_fn, has_aux=True))
            t = timeit(lambda: g(sp, ss, x_cb), n=30)
            print(f"fat model C={C} norm={tag}: grad {t*1e3:.2f} ms")


if __name__ == "__main__":
    main()

"""Memory-observability smoke (ci.sh; docs/OBSERVABILITY.md "Memory &
compilation").

A CPU-only end-to-end pass over the whole memory plane
(fedml_tpu/core/memscope.py):

1. two compiled sims at different cohort sizes leave ``mem.program.*``
   accounting whose ARGUMENT bytes grow with C (the O(C) stacked-round
   law the bulk-client engine must flatten) and ``mem.compile_s``
   histogram entries per program family;
2. the donation audit passes on the real fused round (ServerState and
   the EF residual are donated scan carries — 0 misses) AND flags an
   intentionally-undonated control program (>= 1 miss + one flight
   event naming it);
3. the live monitor samples on the RSS fallback (CPU devices report no
   ``memory_stats``) with the source marked, and the headroom flight
   event fires exactly once when the threshold is crossed;
4. ``/metrics`` exposes the ``mem.*`` vocabulary over real HTTP and
   ``/statusz`` serves the ``memory`` section (per-device readings,
   program table, donation counts);
5. the bench stage shape: ``peak_round_hbm_mb_c{8,64,256}_k{1,8}``
   records land in a bench-artifact-style JSONL, carry the CPU
   fallback mark, diff lower-is-better under scripts/bench_diff.py,
   and a fallback-vs-clean pair is REFUSED for the new unit too.

Usage: python scripts/mem_smoke.py <workdir>
"""

from __future__ import annotations

import json
import os
import sys
import urllib.request

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> int:
    workdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/mem_smoke"
    os.makedirs(workdir, exist_ok=True)

    import jax

    from fedml_tpu.algorithms.fedavg import FedAvgSim
    from fedml_tpu.config import (
        DataConfig, ExperimentConfig, FedConfig, ModelConfig,
        TrainConfig,
    )
    from fedml_tpu.core import memscope as M
    from fedml_tpu.core import telemetry
    from fedml_tpu.data.loaders import load_dataset
    from fedml_tpu.models import create_model

    tdir = os.path.join(workdir, "telemetry")
    telemetry.configure(telemetry_dir=tdir, rank=0, metrics_port=0)

    def cfg(c, k=1):
        # synthetic_1_1: per-client sample draws — the dataset (and so
        # the round program's ARGUMENT bytes) scales with C, which is
        # exactly the growth law assertion 1 pins
        return ExperimentConfig(
            data=DataConfig(dataset="synthetic_1_1", num_clients=c,
                            batch_size=16, seed=0),
            model=ModelConfig(name="lr", num_classes=10,
                              input_shape=(60,)),
            train=TrainConfig(lr=0.1, epochs=1, cohort_fused=False),
            fed=FedConfig(num_rounds=max(2, k), clients_per_round=c,
                          eval_every=10**9, fuse_rounds=k),
            seed=0,
        )

    def build(c, k=1):
        conf = cfg(c, k)
        return FedAvgSim(create_model(conf.model),
                         load_dataset(conf.data), conf)

    # -- 1. per-program accounting grows with C --------------------------
    small_c, big_c = 4, 8
    for c in (small_c, big_c):
        sim = build(c)
        state = sim.init()
        for _ in range(2):
            state, _ = sim.run_round(state)
        jax.block_until_ready(jax.tree.leaves(state))
        del sim, state
    small = M.program_record("sim_round", small_c)
    big = M.program_record("sim_round", big_c)
    assert small and big, (
        f"mem.program accounting missing: {sorted(M.program_table())}"
    )
    assert big["argument_bytes"] > small["argument_bytes"], (
        f"argument bytes must grow with C: "
        f"C={small_c} -> {small['argument_bytes']}, "
        f"C={big_c} -> {big['argument_bytes']}"
    )
    snap = telemetry.METRICS.snapshot()
    compile_hists = {
        k: v for k, v in snap["histograms"].items()
        if k.startswith("mem.compile_s.")
    }
    assert compile_hists and all(
        v["count"] >= 1 and v["sum"] > 0 for v in compile_hists.values()
    ), f"mem.compile_s entries missing: {sorted(snap['histograms'])}"
    gauges = snap["gauges"]
    prog_gauges = [g for g in gauges if g.startswith("mem.program.")]
    assert prog_gauges, "mem.program.* gauges missing"

    # -- 2. donation audit: real fused round passes, control flagged -----
    fsim = build(small_c, k=2)
    fstate = fsim.init()
    fstate, _ = fsim.run_block(fstate, 2)
    jax.block_until_ready(jax.tree.leaves(fstate))
    c0 = telemetry.METRICS.snapshot()["counters"]
    assert c0.get("mem.donation_audits", 0) >= 1, c0
    assert c0.get("mem.donation_misses", 0) == 0, (
        f"the fused round's donated carries must be consumed: {c0}"
    )
    # control: a program that does NOT donate its input — the audit
    # must flag the live buffer as a donation miss
    import jax.numpy as jnp

    control_in = jnp.ones((32, 32))
    undonated = jax.jit(lambda x: x * 2.0)
    jax.block_until_ready(undonated(control_in))
    ok = M.audit_donation("control_undonated", 0,
                          jax.tree.leaves(control_in))
    assert not ok, "the undonated control must fail the audit"
    c1 = telemetry.METRICS.snapshot()["counters"]
    assert c1.get("mem.donation_misses", 0) >= 1, c1
    events = [e for e in telemetry.RECORDER._ring
              if e.get("kind") == "mem_donation_miss"]
    assert events and "control_undonated" in events[-1]["program"], (
        "the donation-miss flight event must name the program"
    )

    # -- 3. monitor: RSS fallback marked, headroom event fires once ------
    sample = M.MONITOR.sample()
    assert sample is not None and sample["bytes_in_use"] > 0, sample
    assert sample["source"] in ("device", "rss"), sample
    if sample["source"] == "rss":
        assert telemetry.METRICS.snapshot()["gauges"].get(
            "mem.source_rss") == 1.0
    M.MONITOR.headroom_warn = 1e-9  # force a crossing
    M.MONITOR.sample()
    M.MONITOR.sample()
    headroom = [e for e in telemetry.RECORDER._ring
                if e.get("kind") == "mem_headroom"]
    assert len(headroom) == 1, (
        f"headroom flight event must fire exactly once, got "
        f"{len(headroom)}"
    )

    # -- 4. live /metrics + /statusz memory section ----------------------
    port = telemetry.exporter().port
    text = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=5
    ).read().decode()
    assert "mem_bytes_in_use" in text and "mem_program_" in text, (
        "mem.* must ride /metrics"
    )
    assert "mem_compile_s_" in text and "_bucket{le=" in text, text[:500]
    statusz = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/statusz", timeout=5
    ).read().decode())
    memsec = statusz.get("memory")
    assert memsec, f"/statusz memory section missing: {sorted(statusz)}"
    assert memsec["source"] in ("device", "rss")
    assert memsec["devices"] and memsec["programs"], memsec
    assert memsec["donation_misses"] >= 1, memsec

    # -- 5. bench stage: records land + lower-is-better + mixed refusal --
    import bench
    from scripts import bench_diff

    direction, known = bench_diff._direction("MB peak")
    assert (direction, known) == (-1, True), (
        "'MB peak' must diff lower-is-better"
    )
    records = bench.mem_bench_records()
    names = {r["metric"] for r in records}
    want = {f"peak_round_hbm_mb_c{c}_k{k}"
            for c in (8, 64, 256) for k in (1, 8)}
    assert names == want, f"missing records: {want - names}"
    artifact = os.path.join(workdir, "mem_bench.jsonl")
    with open(artifact, "w") as f:
        for r in records:
            # emit()'s fallback rule, applied the same way: CPU-backend
            # measurements are always marked
            if jax.default_backend() == "cpu":
                r = dict(r, fallback="cpu")
            assert r["unit"] == "MB peak" and r["value"] > 0, r
            f.write(json.dumps(r) + "\n")
    loaded = bench_diff.load_bench(artifact)
    assert set(loaded) == want
    # growth law visible in the artifact: C=256 round holds more than
    # the C=8 round at the same K (argument bytes scale with the stack)
    assert (loaded["peak_round_hbm_mb_c256_k1"]["value"]
            > loaded["peak_round_hbm_mb_c8_k1"]["value"])
    # bench_diff refuses a fallback-vs-clean pair for the new unit too
    clean = {k: dict(v) for k, v in loaded.items()}
    for v in clean.values():
        v.pop("fallback", None)
    d = bench_diff.diff_records(loaded, clean, threshold=0.08)
    assert len(d["skipped"]) == len(want) and not d["regressions"], d
    # an honest same-side pair diffs normally (and a doubled peak
    # regresses)
    worse = {k: dict(v, value=v["value"] * 2) for k, v in loaded.items()}
    d2 = bench_diff.diff_records(loaded, worse, threshold=0.08)
    assert len(d2["regressions"]) == len(want), d2

    telemetry.shutdown()
    print(
        f"mem smoke ok: {len(prog_gauges)} program gauges, "
        f"{len(compile_hists)} compile-time families, "
        f"argument bytes {small['argument_bytes']} -> "
        f"{big['argument_bytes']} (C {small_c}->{big_c}), "
        f"donation audits {c1.get('mem.donation_audits', 0)} "
        f"(misses {int(c1.get('mem.donation_misses', 0))}, control "
        f"flagged), source={sample['source']}, "
        f"{len(records)} peak_round_hbm records"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

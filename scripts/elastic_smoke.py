"""CI smoke: an elastic gRPC world grows and shrinks mid-run.

Drives the elastic-membership contract end to end over real sockets
(docs/FAULT_TOLERANCE.md "Elastic membership"): a 1-server + 2-client
gRPC world runs with ``--elastic``; once the world is demonstrably past
round 0, a THIRD client (rank 3 — beyond the launch ``world_size``) is
spawned and must be admitted mid-run with its stable client id; client
rank 2 LEAVEs gracefully after round 3 (clean exit 0, no dead-peer
suspicion). The run must complete every round, the server summary must
record the admission (rank 3 active) and the departure (rank 2 left)
with no dead peers, and the round function must have compiled at most
once per distinct cohort bucket (cohorts 2 and 3 -> buckets 2 and 4 ->
``elastic.compile_cache_misses <= 2``).

Usage::

    python scripts/elastic_smoke.py OUT_DIR
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ROUNDS = 8
LEAVE_AFTER = 3


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def main(out_dir: str) -> int:
    os.makedirs(out_dir, exist_ok=True)
    cfg = {
        "data": {"dataset": "fake_mnist", "num_clients": 3,
                 "batch_size": 32, "partition_method": "homo", "seed": 0},
        "model": {"name": "lr", "num_classes": 10,
                  "input_shape": [28, 28, 1]},
        "train": {"lr": 0.1, "epochs": 1},
        "fed": {"algorithm": "fedavg", "num_rounds": ROUNDS,
                "clients_per_round": 3, "eval_every": ROUNDS},
        "seed": 0,
        "run_name": "elastic",
        "out_dir": out_dir,
    }
    cfg_path = os.path.join(out_dir, "cfg.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    ports = _free_ports(4)  # the late joiner needs an address too
    ip_path = os.path.join(out_dir, "ip.json")
    with open(ip_path, "w") as f:
        json.dump({str(r): ["127.0.0.1", ports[r]] for r in range(4)}, f)
    telemetry_dir = os.path.join(out_dir, "telemetry")
    base = [sys.executable, "-m", "fedml_tpu.experiments.run",
            "--config", cfg_path, "--backend", "grpc",
            "--world_size", "3", "--ip_config", ip_path,
            "--ready_timeout", "120", "--elastic",
            "--checkpoint_every", "1",
            "--telemetry_dir", telemetry_dir,
            "--heartbeat_interval", "0.5", "--heartbeat_timeout", "10",
            "--quorum_fraction", "0.5", "--round_deadline", "60"]
    env = _env()

    def spawn(role, rank=None, extra=()):
        argv = [*base, "--role", role, *extra]
        if rank is not None:
            argv += ["--rank", str(rank)]
        return subprocess.Popen(argv, env=env, cwd=REPO,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)

    procs = {
        1: spawn("client", 1),
        2: spawn("client", 2,
                 extra=("--leave_after_round", str(LEAVE_AFTER))),
    }
    server = spawn("server")

    # admit the LATE JOINER once the world is provably past round 0
    # (checkpoint cadence doubles as the progress signal)
    ckpt_dir = os.path.join(out_dir, "elastic", "ckpt")
    deadline = time.monotonic() + 240
    late = None
    while late is None and time.monotonic() < deadline:
        if server.poll() is not None:
            out = server.communicate()[0]
            for p in procs.values():
                p.kill()
            raise SystemExit(
                f"server exited rc={server.returncode} before the "
                f"late joiner could be spawned:\n{out}"
            )
        steps = []
        if os.path.isdir(ckpt_dir):
            steps = [int(d) for d in os.listdir(ckpt_dir) if d.isdigit()]
        if steps:
            late = spawn("client", 3)
            procs[3] = late
        time.sleep(0.05)
    if late is None:
        server.kill()
        for p in procs.values():
            p.kill()
        raise SystemExit("round-0 checkpoint never appeared")

    s_out = server.communicate(timeout=300)[0]
    outs = {}
    for r, p in procs.items():
        try:
            outs[r] = p.communicate(timeout=60)[0]
        except subprocess.TimeoutExpired:
            p.kill()
            outs[r] = p.communicate()[0]
    if server.returncode != 0:
        raise SystemExit(f"server failed rc={server.returncode}:\n{s_out}")
    summary = json.loads(s_out.strip().splitlines()[-1])

    assert summary["rounds"] == ROUNDS, summary
    assert summary["elastic"] is True, summary
    # the admission: rank 3 (beyond the launch world) ended ACTIVE
    assert 3 in summary["membership"]["active"], summary
    # the departure: rank 2 ended LEFT, never suspected dead
    assert summary["membership"]["left"] == [2], summary
    assert summary["dead_peers"] == [], summary
    assert procs[2].returncode == 0, outs[2]
    leaver = json.loads(outs[2].strip().splitlines()[-1])
    assert leaver["status"] == "left", leaver

    # the compile pin: cohorts 2 and 3 -> buckets 2 and 4 -> at most
    # two round-fn compiles for the whole churn schedule
    with open(os.path.join(telemetry_dir, "metrics_rank0.json")) as f:
        counters = json.load(f).get("counters", {})
    misses = counters.get("elastic.compile_cache_misses", 0)
    hits = counters.get("elastic.compile_cache_hits", 0)
    assert 1 <= misses <= 2, counters
    assert hits >= ROUNDS - misses, counters
    assert counters.get("membership.joins", 0) >= 1, counters
    assert counters.get("membership.leaves", 0) >= 1, counters

    print(json.dumps({
        "elastic_smoke": "ok",
        "rounds": summary["rounds"],
        "membership": summary["membership"],
        "compile_cache": {"misses": misses, "hits": hits},
        "loss": summary.get("loss"),
    }))
    return 0


if __name__ == "__main__":
    if len(sys.argv) != 2:
        raise SystemExit("usage: elastic_smoke.py OUT_DIR")
    sys.exit(main(sys.argv[1]))
